"""Model layer of the serving engine: GQA-aware, tp-sharded KV-cache
decode for ``models/llama.py``.

Two fixed-shape jitted functions per decoder (the vLLM/Orca split):

- ``prefill`` — run one request's prompt through the full causal
  forward (the training ``flash_attention`` path, sp=1), write its
  K/V into the request's cache SLOT, and sample the first output
  token.  Prompt lengths are BUCKETED (padded up to the next bucket
  size) so the number of compiled prefill executables is bounded by
  the bucket count, not by the number of distinct prompt lengths.
- ``decode_step`` — one token for ALL slots at once: embed each
  slot's current token, append its K/V at the slot's position, attend
  over the slot's cached history, sample the next token.  Slots are
  mathematically independent rows (per-row matmuls, per-slot
  attention, per-slot PRNG keys folded with the token POSITION), so a
  request decoded in a full batch is bitwise-equal to the same
  request decoded alone — the property continuous batching needs to
  be a scheduling choice rather than a math choice.

Sharding: weights keep the training layout (``Llama.param_specs`` —
QKV/gate/up column-parallel, o/down row-parallel, vocab sharded
through embed/head); the KV cache shards its KV-HEAD dim over the
``model`` axis, so each tp shard caches exactly the heads it
computes.  The samplers (``parallel/tp.py``: ``sharded_argmax`` /
``sharded_sample``) combine over the model axis with the (value, id)
max-reduction trick and full-vocab Gumbel draws, which makes sampled
ids bitwise layout-invariant across tp=1 vs tp>1 meshes.

Everything runs in unchecked manual mode (``check_vma=False``) with
explicit collectives only — the forward-only serving path works
identically on the 0.4.x-shimmed jax (``compat.py``) and current jax.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from theanompi_tpu.models.llama import (
    Llama,
    _heads,
    _unheads,
    rms_norm,
    rope,
    rope_at,
)
from theanompi_tpu.ops.attention import NEG_INF, flash_attention
from theanompi_tpu.parallel import MODEL_AXIS, dp_replicas, make_mesh
from theanompi_tpu.parallel import tp as tp_lib


def default_prefill_buckets(max_prefill: int, base: int = 16) -> tuple:
    """Power-of-two bucket ladder ``base, 2*base, ...`` capped at
    ``max_prefill`` (always included) — one compile per bucket."""
    out = []
    b = base
    while b < max_prefill:
        out.append(b)
        b *= 2
    out.append(max_prefill)
    return tuple(out)


class LlamaDecoder:
    """KV-cache decoder over a compiled (and typically
    checkpoint-restored) ``Llama`` — see module docstring.

    The decoder owns the cache (``max_slots`` request slots of
    ``max_seq`` positions each) and exposes the two host-callable
    device functions the engine schedules:

    - ``prefill(slot, prompt_ids, key, temperature) -> first token``
    - ``decode(tokens, lengths, keys, temps) -> next tokens [S]``

    Serving composes with tensor parallelism only: ``pp > 1``,
    ``sp > 1`` and MoE models are not yet servable.
    """

    def __init__(
        self,
        model: Llama,
        *,
        max_slots: int = 8,
        max_seq: int | None = None,
        prefill_buckets: tuple | None = None,
    ):
        if model.mesh is None or model.params is None:
            raise ValueError(
                "LlamaDecoder needs a compiled model: call "
                "build_model() + compile_iter_fns() (then load() for "
                "checkpoint weights) before serving"
            )
        if model.pp > 1 or model.sp > 1 or model.n_experts:
            raise NotImplementedError(
                "serving composes with tensor parallelism only — "
                f"pp={model.pp}, sp={model.sp}, "
                f"n_experts={model.n_experts} are not yet servable"
            )
        self.model = model
        self.mesh = model.mesh
        self.max_slots = int(max_slots)
        self.max_seq = int(max_seq or model.seq_len)
        # decode appends one position past the prompt per token, so
        # the longest servable prompt leaves room for >= 1 new token
        self.max_prefill = self.max_seq - 1
        self.prefill_buckets = tuple(
            sorted(prefill_buckets)
            if prefill_buckets else default_prefill_buckets(self.max_prefill)
        )
        assert self.prefill_buckets[-1] == self.max_prefill, (
            f"largest prefill bucket {self.prefill_buckets[-1]} must "
            f"equal max_prefill {self.max_prefill}"
        )

        m = model
        self._h_loc = m.n_heads // m.tp
        self._hkv_loc = m.n_kv_heads // m.tp
        self._rep = self._h_loc // self._hkv_loc
        self._hd = m.head_dim
        self._cdtype = m.compute_dtype

        # KV cache: one {k, v} pair per layer, [S, Hkv/tp, T, hd] in
        # compute dtype, kv-head dim sharded over the model axis
        kv_spec = P(None, MODEL_AXIS, None, None)
        self._cache_specs = [
            {"k": kv_spec, "v": kv_spec} for _ in range(m.n_layers)
        ]
        shape = (self.max_slots, m.n_kv_heads, self.max_seq, self._hd)
        sharding = NamedSharding(self.mesh, kv_spec)

        def _zeros():
            z = jnp.zeros(shape, self._cdtype)
            return [{"k": z, "v": z} for _ in range(m.n_layers)]

        self.cache = jax.jit(
            _zeros,
            out_shardings=[
                {"k": sharding, "v": sharding} for _ in range(m.n_layers)
            ],
        )()

        # compiled variants: decode keyed by the static all-greedy
        # flag, prefill by (bucket, greedy) — the compile count is
        # bounded by 2 x (1 + bucket-ladder length)
        self._decode_fns: dict[bool, object] = {}
        self._prefill_fns: dict[tuple[int, bool], object] = {}

    # -- device bodies (run on LOCAL shards inside shard_map) -------------

    def _mlp(self, p, x):
        xn = rms_norm(x, p["mlp_norm"])
        gate = jax.nn.silu(tp_lib.col_parallel(xn, p["w_gate"]))
        up = tp_lib.col_parallel(xn, p["w_up"])
        return x + tp_lib.row_parallel(gate * up, p["w_down"]).astype(
            x.dtype
        )

    def _sample(self, logits, keys, pos, temps, greedy: bool):
        """Token ids from [N, V/tp] logits.  ``greedy=True`` is the
        static all-greedy fast path: pure ``sharded_argmax``, no
        Gumbel draw, no key fold — bitwise-identical ids to the
        sampling path at temperature<=0 (both argmax the same f32
        logits), so batch composition never changes outputs."""
        if greedy:
            return tp_lib.sharded_argmax(
                logits.astype(jnp.float32), self.model.vocab
            )
        # the token that will sit at position pos+1 samples with
        # fold_in(request_key, pos+1) — position-keyed, so batched
        # and single-request decodes draw identical noise
        skeys = jax.vmap(jax.random.fold_in)(keys, pos + 1)
        return tp_lib.sharded_sample(
            logits, self.model.vocab, skeys, temps
        )

    def _decode_body(self, params, cache, tokens, lengths, keys, temps,
                     greedy: bool):
        """One token for all slots.  tokens/lengths [S] int32, keys
        [S, 2] uint32, temps [S] f32 -> (cache, next_tokens [S])."""
        m = self.model
        s = self.max_slots
        hd, h_loc, hkv_loc, rep = (
            self._hd, self._h_loc, self._hkv_loc, self._rep
        )
        x = tp_lib.embed_lookup(
            tokens[:, None], params["embed"], m.vocab
        )[:, 0, :].astype(self._cdtype)                       # [S, D]
        pos = lengths                          # write position per slot
        valid = (
            jnp.arange(self.max_seq)[None, :] <= pos[:, None]
        )[:, None, None, :]                            # [S, 1, 1, T]

        new_cache = []
        for layer_cache, p in zip(cache, params["layers"]):
            xn = rms_norm(x, p["attn_norm"])
            q = tp_lib.col_parallel(xn, p["wq"]).reshape(s, h_loc, hd)
            k = tp_lib.col_parallel(xn, p["wk"]).reshape(s, hkv_loc, hd)
            v = tp_lib.col_parallel(xn, p["wv"]).reshape(s, hkv_loc, hd)
            q = rope_at(q, pos)
            k = rope_at(k, pos)
            # append this token's K/V at each slot's own position
            write = jax.vmap(
                lambda c, u, i: lax.dynamic_update_slice(
                    c, u[:, None, :], (0, i, 0)
                )
            )
            ck = write(layer_cache["k"], k.astype(self._cdtype), pos)
            cv = write(layer_cache["v"], v.astype(self._cdtype), pos)
            new_cache.append({"k": ck, "v": cv})
            # GQA attention against the cached history: group the
            # query heads by their KV head, no repeat materialized
            qg = q.reshape(s, hkv_loc, rep, hd)
            scores = jnp.einsum("skrd,sktd->skrt", qg, ck).astype(
                jnp.float32
            ) * (hd ** -0.5)
            scores = jnp.where(valid, scores, NEG_INF)
            probs = jax.nn.softmax(scores, axis=-1)
            o = jnp.einsum(
                "skrt,sktd->skrd", probs.astype(cv.dtype), cv
            ).reshape(s, h_loc * hd)
            x = x + tp_lib.row_parallel(o, p["wo"]).astype(self._cdtype)
            x = self._mlp(p, x)

        xf = rms_norm(x, params["final_norm"])
        logits = tp_lib.col_parallel(xf, params["lm_head"])  # [S, V/tp]
        nxt = self._sample(logits, keys, pos, temps, greedy)
        return new_cache, nxt

    def _prefill_body(self, params, cache, ids, slot, length, key, temp,
                      greedy: bool):
        """Prompt forward for ONE request: ids [t_bucket] int32
        (zero-padded past ``length``), slot/length scalars.  Writes
        K/V rows [0, t_bucket) of ``slot`` (rows >= length hold
        padding garbage, but decode overwrites position p before any
        token attends to it — positions are filled strictly in order)
        and samples the first output token at position ``length``."""
        m = self.model
        hd, h_loc, hkv_loc, rep = (
            self._hd, self._h_loc, self._hkv_loc, self._rep
        )
        t = ids.shape[0]
        x = tp_lib.embed_lookup(
            ids[None, :], params["embed"], m.vocab
        ).astype(self._cdtype)                              # [1, t, D]
        pos = jnp.arange(t)

        new_cache = []
        for layer_cache, p in zip(cache, params["layers"]):
            xn = rms_norm(x, p["attn_norm"])
            q = _heads(tp_lib.col_parallel(xn, p["wq"]), h_loc, hd)
            k = _heads(tp_lib.col_parallel(xn, p["wk"]), hkv_loc, hd)
            v = _heads(tp_lib.col_parallel(xn, p["wv"]), hkv_loc, hd)
            q = rope(q, pos)
            k = rope(k, pos)
            kc = k.astype(self._cdtype)
            vc = v.astype(self._cdtype)
            new_cache.append({
                "k": lax.dynamic_update_slice(
                    layer_cache["k"], kc, (slot, 0, 0, 0)
                ),
                "v": lax.dynamic_update_slice(
                    layer_cache["v"], vc, (slot, 0, 0, 0)
                ),
            })
            if rep != 1:
                k = jnp.repeat(k, rep, axis=1)
                v = jnp.repeat(v, rep, axis=1)
            o = flash_attention(q, k, v, causal=True)
            x = x + tp_lib.row_parallel(
                _unheads(o), p["wo"]
            ).astype(self._cdtype)
            x = self._mlp(p, x)

        xf = rms_norm(x, params["final_norm"])
        # only the LAST PROMPT TOKEN's logits matter — slice before
        # the head so the [t, V] logits never materialize
        x_last = lax.dynamic_slice(
            xf, (0, length - 1, 0), (1, 1, xf.shape[-1])
        )[:, 0, :]                                          # [1, D]
        logits = tp_lib.col_parallel(x_last, params["lm_head"])
        # the first generated token sits at position `length`:
        # _sample folds pos+1, so pass length-1 (same fold policy as
        # decode — token at position p always draws fold_in(key, p))
        tok = self._sample(
            logits, key[None], jnp.reshape(length - 1, (1,)),
            temp[None], greedy,
        )[0]
        return new_cache, tok

    # -- compiled entry points --------------------------------------------

    def _decode_jit(self, greedy: bool):
        fn = self._decode_fns.get(greedy)
        if fn is None:
            import functools

            rep = P()
            fn = jax.jit(
                jax.shard_map(
                    functools.partial(self._decode_body, greedy=greedy),
                    mesh=self.mesh,
                    in_specs=(self.model._specs, self._cache_specs,
                              rep, rep, rep, rep),
                    out_specs=(self._cache_specs, rep),
                    check_vma=False,
                ),
                donate_argnums=(1,),
            )
            self._decode_fns[greedy] = fn
        return fn

    def _prefill_jit(self, bucket: int, greedy: bool):
        fn = self._prefill_fns.get((bucket, greedy))
        if fn is None:
            import functools

            rep = P()
            fn = jax.jit(
                jax.shard_map(
                    functools.partial(
                        self._prefill_body, greedy=greedy
                    ),
                    mesh=self.mesh,
                    in_specs=(self.model._specs, self._cache_specs,
                              rep, rep, rep, rep, rep),
                    out_specs=(self._cache_specs, rep),
                    check_vma=False,
                ),
                donate_argnums=(1,),
            )
            self._prefill_fns[(bucket, greedy)] = fn
        return fn

    def bucket_for(self, prompt_len: int) -> int:
        """Smallest compiled-shape bucket covering ``prompt_len``."""
        if not 1 <= prompt_len <= self.max_prefill:
            raise ValueError(
                f"prompt length {prompt_len} outside servable range "
                f"[1, {self.max_prefill}] (max_seq {self.max_seq} "
                f"leaves one position for generation)"
            )
        for b in self.prefill_buckets:
            if b >= prompt_len:
                return b
        raise AssertionError("unreachable: last bucket == max_prefill")

    # -- host API (the engine's two scheduling primitives) ----------------

    def prefill(self, slot: int, prompt_ids, key, temperature) -> int:
        """Run one prompt into ``slot``; returns the first sampled
        token (host int — reading it IS the TTFT fence)."""
        ids = np.asarray(prompt_ids, np.int32)
        bucket = self.bucket_for(ids.shape[0])
        padded = np.zeros((bucket,), np.int32)
        padded[: ids.shape[0]] = ids
        self.cache, tok = self._prefill_jit(bucket, temperature <= 0)(
            self.model.params, self.cache,
            jnp.asarray(padded),
            jnp.int32(slot), jnp.int32(ids.shape[0]),
            jnp.asarray(key, jnp.uint32),
            jnp.float32(temperature),
        )
        return int(tok)

    def decode(self, tokens, lengths, keys, temps) -> np.ndarray:
        """One decode step for all slots.  Host arrays in, host token
        ids [S] out (the read fences the step).  An all-greedy batch
        (the common case) dispatches the Gumbel-free executable; a
        mixed batch uses the sampling one, whose per-slot
        temperature<=0 branch argmaxes identically."""
        self.cache, nxt = self._decode_jit(
            bool(np.all(np.asarray(temps) <= 0.0))
        )(
            self.model.params, self.cache,
            jnp.asarray(tokens, jnp.int32),
            jnp.asarray(lengths, jnp.int32),
            jnp.asarray(keys, jnp.uint32),
            jnp.asarray(temps, jnp.float32),
        )
        return np.asarray(nxt)

    @property
    def n_prefill_compiles(self) -> int:
        """Compiled prefill variants so far (bounded by 2 x the
        bucket ladder: (bucket, greedy) keys — the compile-count
        guarantee under test)."""
        return len(self._prefill_fns)


def decoder_from_checkpoint(
    config: dict,
    directory: str,
    *,
    mesh=None,
    devices=None,
    **decoder_kw,
) -> LlamaDecoder:
    """The train → checkpoint → serve path in one call: build a
    ``Llama`` for the SERVING layout (``config['tp']`` etc.), restore
    weights through ``model.load`` — including sharded checkpoints
    and the validated/quarantine fallback path — and wrap it in a
    ``LlamaDecoder``.  The checkpoint may come from any training
    layout; npz and sharded formats both reload across layouts."""
    model = Llama(config)
    if mesh is None:
        mesh = make_mesh(
            data=1, model=model.tp,
            devices=devices,
        )
    model.build_model(n_replicas=dp_replicas(mesh))
    model.compile_iter_fns(mesh=mesh)
    if not model.load(directory):
        raise FileNotFoundError(
            f"no loadable checkpoint under {directory!r}"
        )
    return LlamaDecoder(model, **decoder_kw)
