"""Replica layer of the serving fleet: one `Engine` behind a
health-stamped loop, reachable in-process or over TCP.

The fleet's unit of capacity is a REPLICA: one decoder + one engine
driven by one owner loop that, every iteration,

1. fires any targeted fault drill (``utils/faults.py`` —
   ``TM_FAULT_AT="<replica_index>:<busy_iter>:die_replica"`` kills
   THIS loop exactly the way the PR 3 fault matrix kills a training
   worker: same env machinery, different clock — the iteration field
   counts BUSY engine iterations, so a drill at iteration k dies
   with requests provably in flight),
2. runs one ``Engine.step()`` (shed → admit → prefill → decode),
3. stamps a supervisor-style heartbeat (monotonic progress + wall
   time) — the router's watchdog judges liveness by FRESH stamps,
   exactly like ``utils/supervisor.py`` judges a training worker.

Two transports share that loop:

- :class:`InProcessReplica` — the loop on a thread in the router's
  process.  Zero wire cost; the deployment shape when replicas are
  meshes of one pod slice.  ``pause()``/``resume()`` simulate a
  stalled loop (a stuck collective) for the watchdog drills, and
  ``restart()`` relaunches a dead loop over the same engine — its
  abandoned requests were requeued by the router, so the restart
  sheds their engine-side futures (``Engine.abandon_all``) and the
  fresh heartbeats let the router's monitor REJOIN the replica
  automatically.
- :class:`ReplicaServer` / :class:`TCPReplicaClient` — the same loop
  in another process, reached over the repo's one TCP wire (the
  length-prefixed pickle frames of ``parallel/center_server.py``).
  The client keeps ONE connection: a reader thread resolves result
  frames into local futures (out-of-order safe — frames carry the
  request id), and a pinger thread refreshes a cached heartbeat +
  load snapshot so the router's health check never blocks on the
  network.  A dropped connection marks the client dead; the router
  requeues its in-flight requests — the fleet twin of the engine's
  "every future resolves" guarantee.

``python -m theanompi_tpu.serving.replica --spec-json '{...}'``
hosts a checkpoint-restored decoder as a replica child (the bench's
multi-process fleet and the ``serving_fleet`` smoke use it); it
prints ``REPLICA_READY <port>`` once serving.
"""

from __future__ import annotations

import itertools
import socket
import threading
import time

from theanompi_tpu.serving.engine import (
    Engine,
    Request,
    Result,
    ServingFuture,
)
from theanompi_tpu.utils.faults import maybe_inject_fault


def result_to_dict(r: Result) -> dict:
    return {
        "status": r.status, "finish_reason": r.finish_reason,
        "tokens": list(r.tokens), "ttft_s": r.ttft_s,
        "tpot_s": r.tpot_s, "queued_s": r.queued_s, "e2e_s": r.e2e_s,
        # numpy KV payloads ride the pickle frames as-is
        "handoff": r.handoff,
        # the request's span flight record (obs/tracer.py) — the
        # router ingests it, so the tree survives this process
        "spans": list(r.spans),
    }


def result_from_dict(d: dict) -> Result:
    d = dict(d)
    d.setdefault("spans", [])   # pre-tracing peers
    return Result(**d)


class InProcessReplica:
    """One engine + its owner loop thread + a heartbeat the router
    watches.  The loop stamps ``{"progress", "time", "status"}`` per
    iteration (idle iterations refresh ``time`` without advancing
    ``progress`` — an idle replica is alive); a loop that raises
    (``ReplicaDied`` from a fault drill, or any real crash) leaves
    ``dead=True`` with the cause recorded and its heartbeat stale.
    """

    #: dispatch roles a replica can declare (serving v4): "unified"
    #: serves end-to-end; "prefill" specialists take prefill-only
    #: dispatches and ship KV handoffs; "decode" specialists receive
    #: handoffs and run pure decode.  The ROUTER enforces the policy
    #: — the engine underneath is identical, which is what makes the
    #: unified fallback safe.
    ROLES = ("unified", "prefill", "decode")

    def __init__(self, engine: Engine, *, name: str | None = None,
                 index: int = 0, idle_sleep_s: float = 1e-3,
                 role: str = "unified"):
        self.engine = engine
        self.index = int(index)
        self.name = name if name is not None else f"replica{index}"
        self.idle_sleep_s = float(idle_sleep_s)
        if role not in self.ROLES:
            raise ValueError(
                f"role must be one of {self.ROLES}, got {role!r}"
            )
        self.role = role
        self._steps = 0
        self._hb = {"progress": 0, "time": 0.0, "status": "starting"}
        self._stop = threading.Event()
        self._paused = threading.Event()
        self._thread: threading.Thread | None = None
        self.dead = False
        self.death_cause: str | None = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "InProcessReplica":
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError(f"{self.name} already running")
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name=f"tm-{self.name}", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        try:
            while not self._stop.is_set():
                if self._paused.is_set():
                    # simulated stall: alive thread, NO fresh stamps —
                    # exactly what a stuck collective looks like to
                    # the router's watchdog
                    time.sleep(1e-3)
                    continue
                maybe_inject_fault(self.index, self._steps)
                busy = self.engine.step()
                if busy:
                    # the fault/progress clock counts BUSY iterations
                    # (idle spins tick ~1000/s — a drill targeting
                    # "iteration 3" means the 3rd iteration that did
                    # work, so the dying replica provably has
                    # requests in flight)
                    self._steps += 1
                self._hb = {
                    "progress": self._steps, "time": time.time(),
                    "status": "running",
                }
                if not busy and self.engine.queue_depth() == 0:
                    time.sleep(self.idle_sleep_s)
        except BaseException as e:  # noqa: BLE001 - a dying replica is DATA
            self.dead = True
            self.death_cause = f"{type(e).__name__}: {e}"
            self._hb = dict(self._hb, status="dead")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)

    def restart(self) -> "InProcessReplica":
        """Relaunch a dead (or stopped) replica over the SAME engine
        and decoder.  The router already requeued the dead loop's
        pending requests elsewhere, so their engine-side futures are
        shed (never dangle) and their slots/blocks freed before the
        fresh loop starts; the new loop's heartbeats are what make
        the router's monitor rejoin this replica."""
        if self._thread is not None and self._thread.is_alive() \
                and not self.dead:
            raise RuntimeError(f"{self.name} still running")
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
        self.engine.abandon_all(reason="restart")
        self.dead = False
        self.death_cause = None
        self._paused.clear()
        self._thread = None
        return self.start()

    # -- test/ops hooks (simulated stall) ----------------------------------

    def pause(self) -> None:
        self._paused.set()

    def resume(self) -> None:
        self._paused.clear()

    # -- the replica protocol (what the router consumes) -------------------

    def submit(self, request: Request) -> ServingFuture:
        return self.engine.submit(request)

    def load(self) -> int:
        """Queue depth + occupied slots — the least-loaded policy's
        scalar."""
        return self.engine.queue_depth() + self.engine.active_slots()

    def slots(self) -> int:
        """Decode-slot capacity — the autoscaler's denominator when
        it turns fleet-wide outstanding work into a pressure
        signal."""
        return self.engine.decoder.max_slots

    def heartbeat(self) -> dict:
        return dict(self._hb)

    def alive(self) -> bool:
        return (
            not self.dead
            and self._thread is not None
            and self._thread.is_alive()
        )

    def recorder_state(self) -> dict:
        return self.engine.recorder.state_dict()

    def paging_stats(self) -> dict | None:
        return self.engine.paging_stats()

    def trace_state(self) -> list:
        """The engine's span ring (flight-recorder salvage hook: the
        router pulls this when the loop dies, so in-flight requests'
        spans outlive the crash)."""
        tr = self.engine.tracer
        return tr.spans() if tr is not None else []

    def metrics_txt(self) -> str:
        return self.engine.recorder.metrics_txt()

    def reset_stats(self) -> None:
        """Fresh recorder + cleared radix cache — the bench's
        between-arm reset."""
        from theanompi_tpu.utils.recorder import ServingRecorder

        self.engine.recorder = ServingRecorder(
            max_slots=self.engine.decoder.max_slots
        )
        cache = getattr(self.engine.decoder, "prefix_cache", None)
        if cache is not None:
            cache.clear()


# ---------------------------------------------------------------------------
# TCP transport (reuses the center-server frame wire)
# ---------------------------------------------------------------------------


class ReplicaServer:
    """Host an :class:`InProcessReplica` behind the center-server TCP
    frames.  Commands (client → server):

    - ``("submit", {"rid", "prompt", "max_tokens", "temperature",
      "seed", "deadline_s"})`` — no reply frame; the terminal
      ``("result", (rid, result_dict))`` is PUSHED when the engine
      resolves the request's future (out of order across rids).
    - ``("ping", nonce)`` → ``("reply", (nonce, {"hb", "load",
      "alive", "name"}))`` — the health/load snapshot.
    - ``("stats", nonce)`` → recorder state + paging stats.
    - ``("reset", nonce)`` — fresh recorder, cleared radix cache.
    - ``("shutdown", None)`` — stop the engine loop and the server.
    """

    def __init__(self, engine: Engine, *, name: str = "replica",
                 index: int = 0, host: str = "127.0.0.1",
                 port: int = 0, role: str = "unified",
                 send_timeout_s: float = 30.0):
        self.replica = InProcessReplica(engine, name=name, index=index,
                                        role=role)
        self.send_timeout_s = float(send_timeout_s)
        self._stopped = threading.Event()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.address = (host, self._sock.getsockname()[1])
        self._accept_thread = threading.Thread(
            target=self._serve, name=f"tm-{name}-srv", daemon=True
        )

    def start(self) -> "ReplicaServer":
        self.replica.start()
        self._accept_thread.start()
        return self

    def _serve(self) -> None:
        while not self._stopped.is_set():
            try:
                self._sock.settimeout(0.2)
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(
                target=self._client, args=(conn,), daemon=True
            ).start()

    def _client(self, conn: socket.socket) -> None:
        from theanompi_tpu.parallel.center_server import (
            recv_frame,
            send_frame,
        )

        send_lock = threading.Lock()

        def push(frame) -> None:
            # engine-thread callbacks race the command loop for the
            # socket; a dead connection just drops the frame (the
            # router requeues on the health signal, not on delivery).
            # send_timeout_s bounds the write: a peer that stops
            # READING (wedged client, full TCP buffer) would leave a
            # bare sendall blocked forever while holding send_lock —
            # parking every engine-thread result callback behind it
            # and stalling the replica (tmcheck TM103 found it).
            try:
                with send_lock:
                    send_frame(conn, frame,
                               timeout_s=self.send_timeout_s)
            except (OSError, ConnectionError):
                # a timed-out send may have written PART of a frame —
                # the length-prefixed stream is desynced, so the
                # connection is unusable: close it so the command
                # loop's recv fails and the client's reader marks the
                # wire dead (router requeues), instead of appending
                # the next frame at an arbitrary byte offset
                try:
                    conn.close()
                except OSError:
                    pass

        try:
            with conn:
                while True:
                    cmd, payload = recv_frame(conn)
                    if cmd == "submit":
                        rid = payload["rid"]
                        req = Request(
                            prompt=list(payload["prompt"]),
                            max_tokens=int(payload["max_tokens"]),
                            temperature=float(payload["temperature"]),
                            deadline_s=payload.get("deadline_s"),
                            seed=int(payload.get("seed", 0)),
                            prefill_only=bool(
                                payload.get("prefill_only", False)
                            ),
                            handoff=payload.get("handoff"),
                            trace=payload.get("trace"),
                        )
                        self.replica.submit(req).add_done_callback(
                            lambda r, rid=rid: push(
                                ("result", (rid, result_to_dict(r)))
                            )
                        )
                    elif cmd == "trace":
                        push(("reply", (payload, {
                            "spans": self.replica.trace_state(),
                        })))
                    elif cmd == "metrics":
                        push(("reply", (payload, {
                            "text": self.replica.metrics_txt(),
                        })))
                    elif cmd == "ping":
                        push(("reply", (payload, {
                            "hb": self.replica.heartbeat(),
                            "load": self.replica.load(),
                            "alive": self.replica.alive(),
                            "name": self.replica.name,
                            "role": self.replica.role,
                            "slots": self.replica.slots(),
                        })))
                    elif cmd == "stats":
                        push(("reply", (payload, {
                            "recorder": self.replica.recorder_state(),
                            "paging": self.replica.paging_stats(),
                            "hb": self.replica.heartbeat(),
                        })))
                    elif cmd == "reset":
                        self.replica.reset_stats()
                        push(("reply", (payload, "ok")))
                    elif cmd == "shutdown":
                        self.stop()
                        return
                    else:
                        push(("reply", (payload, f"unknown {cmd!r}")))
        except (ConnectionError, EOFError, OSError):
            return

    def stop(self) -> None:
        self._stopped.set()
        self.replica.stop()
        try:
            self._sock.close()
        except OSError:
            pass

    def wait(self, timeout: float | None = None) -> bool:
        """Block until shutdown (the child entry point's main loop)."""
        return self._stopped.wait(timeout)


class TCPReplicaClient:
    """Router-side handle to a :class:`ReplicaServer` — implements
    the same replica protocol as :class:`InProcessReplica`, so the
    router treats both uniformly.

    ``load()`` and ``heartbeat()`` serve the PINGER's cached
    snapshot (refreshed every ``ping_interval_s``): the health check
    must never block the router on a sick network, and a stale
    snapshot is precisely what "stalled" means.  Any wire failure
    marks the client dead and resolves its outstanding futures as
    shed "replica_dead" — the router requeues them on the spot, and
    a direct caller's ``result()`` never hangs.
    """

    def __init__(self, address: tuple, *, name: str | None = None,
                 connect_timeout: float = 120.0,
                 ping_interval_s: float = 0.05,
                 ping_timeout_s: float = 10.0,
                 send_timeout_s: float = 30.0,
                 role: str = "unified", slots: int = 1):
        self.address = tuple(address)
        self.name = name if name is not None else f"tcp:{address[1]}"
        self.send_timeout_s = float(send_timeout_s)
        self.ping_timeout_s = float(ping_timeout_s)
        # role/slots seed from the caller (who launched the replica
        # and knows its spec); pongs carrying the server's own values
        # overwrite them, so a default-constructed client converges
        # to the truth after one ping round trip
        self.role = role
        self._slots = int(slots)
        self.dead = False
        self._rid = itertools.count()
        self._nonce = itertools.count()
        self._futures: dict[int, ServingFuture] = {}
        self._replies: dict[int, list] = {}   # nonce -> [event, payload]
        self._lock = threading.Lock()
        self._send_lock = threading.Lock()
        self._hb: dict = {"progress": -1, "time": 0.0,
                          "status": "connecting"}
        self._load = 0

        deadline = time.monotonic() + connect_timeout
        delay = 0.1
        while True:
            try:
                self._sock = socket.create_connection(
                    self.address, timeout=60.0
                )
                self._sock.settimeout(None)
                break
            except OSError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(delay)
                delay = min(delay * 2, 2.0)

        self._reader = threading.Thread(
            target=self._read_loop, name=f"tm-{self.name}-rd",
            daemon=True,
        )
        self._reader.start()
        self._pinger = threading.Thread(
            target=self._ping_loop, args=(float(ping_interval_s),),
            name=f"tm-{self.name}-hb", daemon=True,
        )
        self._pinger.start()

    # -- wire --------------------------------------------------------------

    def _send(self, frame) -> None:
        from theanompi_tpu.parallel.center_server import send_frame

        # send_timeout_s bounds the write (socket.timeout is an
        # OSError): the router dispatches under ITS lock, so an
        # unbounded sendall into a wedged peer would freeze the whole
        # fleet — watchdog included — forever.
        try:
            with self._send_lock:
                send_frame(self._sock, frame,
                           timeout_s=self.send_timeout_s)
        except (OSError, ConnectionError):
            self._mark_dead()
            raise ConnectionError(f"{self.name}: send failed")

    def _read_loop(self) -> None:
        from theanompi_tpu.parallel.center_server import recv_frame

        try:
            while True:
                tag, payload = recv_frame(self._sock)
                if tag == "result":
                    rid, d = payload
                    with self._lock:
                        fut = self._futures.pop(rid, None)
                    if fut is not None:
                        fut._set(result_from_dict(d))
                elif tag == "reply":
                    nonce, data = payload
                    with self._lock:
                        slot = self._replies.get(nonce)
                    if slot is not None:
                        slot[1] = data
                        slot[0].set()
        except (ConnectionError, EOFError, OSError):
            self._mark_dead()

    def _mark_dead(self) -> None:
        self.dead = True
        with self._lock:
            slots = list(self._replies.values())
            futures = list(self._futures.values())
            self._futures.clear()
        for slot in slots:
            slot[0].set()   # unblock command waiters (payload None)
        # Resolve every outstanding submit as "replica_dead" — same
        # shape as the mid-submit death path, so the router requeues
        # immediately instead of waiting out a health-poll interval,
        # and a direct (router-less) caller never hangs on result().
        # MUST run outside self._lock: _set fires the router's
        # done-callback, which takes the router lock — and router
        # paths holding that lock call load(), which takes ours.
        for fut in futures:
            fut._set(Result(status="shed",
                            finish_reason="replica_dead"))

    def _command(self, cmd: str, timeout: float = 30.0,
                 even_if_dead: bool = False):
        """``even_if_dead`` keeps trying the WIRE after the liveness
        verdict went dead: a fault drill that killed the remote
        ENGINE LOOP leaves the frame-serving threads alive, and the
        flight-recorder salvage wants exactly that window.  A truly
        dead socket still fails fast (the send raises)."""
        nonce = next(self._nonce)
        slot = [threading.Event(), None]
        with self._lock:
            self._replies[nonce] = slot
        try:
            self._send((cmd, nonce))
            if not slot[0].wait(timeout) or (
                self.dead and not even_if_dead
            ):
                raise ConnectionError(
                    f"{self.name}: no {cmd} reply"
                )
            if slot[1] is None and self.dead:
                raise ConnectionError(
                    f"{self.name}: wire died before {cmd} reply"
                )
            return slot[1]
        finally:
            with self._lock:
                self._replies.pop(nonce, None)

    def _ping_loop(self, interval: float) -> None:
        while not self.dead:
            try:
                data = self._command("ping",
                                     timeout=self.ping_timeout_s)
            except ConnectionError:
                if self.dead:
                    return
                # transient: the reply timed out but the wire is
                # intact (a GIL-heavy compile can stall the replica
                # >10s).  Keep pinging — exiting here would freeze
                # heartbeat() forever, so the router could never see
                # a fresh beat and the member could never rejoin.
                # A truly dead socket fails the ping SEND next pass,
                # which marks the client dead and ends the loop.
                continue
            if not data.get("alive", False):
                # the remote LOOP died while the socket lives: a
                # replica-process fault drill that only killed the
                # engine thread still reads as dead fleet-side
                self.dead = True
                return
            self._hb = data["hb"]
            self._load = data["load"]
            if "role" in data:
                self.role = data["role"]
            if "slots" in data:
                self._slots = int(data["slots"])
            time.sleep(interval)

    # -- the replica protocol ----------------------------------------------

    def submit(self, request: Request) -> ServingFuture:
        rid = next(self._rid)
        fut = ServingFuture()
        with self._lock:
            self._futures[rid] = fut
        try:
            self._send(("submit", {
                "rid": rid, "prompt": list(request.prompt),
                "max_tokens": request.max_tokens,
                "temperature": request.temperature,
                "deadline_s": request.deadline_s,
                "seed": request.seed,
                "prefill_only": request.prefill_only,
                "handoff": request.handoff,
                "trace": request.trace,
            }))
        except ConnectionError:
            with self._lock:
                self._futures.pop(rid, None)
            # resolve SHED rather than raise: the router treats a
            # mid-submit death like any other failover (requeue)
            fut._set(Result(status="shed", finish_reason="replica_dead"))
            return fut
        if self.dead:
            # raced _mark_dead's sweep: our future registered after
            # the snapshot and the send still landed in the local
            # buffer, so nobody else will ever resolve it (_set is
            # first-wins — a no-op if the sweep did catch it)
            with self._lock:
                self._futures.pop(rid, None)
            fut._set(Result(status="shed", finish_reason="replica_dead"))
        return fut

    def load(self) -> int:
        """Load for the least-loaded policy.  The remote snapshot is
        only as fresh as the last pong — during a burst of submits it
        still reads 0, which would send EVERY tie-broken request to
        the same member — so take the max with this client's own
        outstanding (submitted, unresolved) count, which is exact for
        the traffic this router originated and available instantly."""
        with self._lock:
            outstanding = len(self._futures)
        return max(self._load, outstanding)

    def slots(self) -> int:
        return self._slots

    def heartbeat(self) -> dict:
        return dict(self._hb)

    def alive(self) -> bool:
        return not self.dead

    def recorder_state(self, timeout: float = 30.0) -> dict:
        return self._command("stats", timeout)["recorder"]

    def stats(self, timeout: float = 30.0) -> dict:
        return self._command("stats", timeout)

    def trace_state(self, timeout: float = 10.0) -> list:
        """Pull the remote engine's span ring — the router's salvage
        hook, so it tries the wire EVEN AFTER the liveness verdict
        went dead (a die_replica drill kills the engine loop, not the
        frame server).  Short timeout: salvage is best-effort."""
        return self._command("trace", timeout,
                             even_if_dead=True)["spans"]

    def metrics_txt(self, timeout: float = 30.0) -> str:
        return self._command("metrics", timeout)["text"]

    def paging_stats(self, timeout: float = 30.0) -> dict | None:
        return self._command("stats", timeout)["paging"]

    def reset_stats(self, timeout: float = 30.0) -> None:
        self._command("reset", timeout)

    def shutdown(self) -> None:
        try:
            self._send(("shutdown", None))
        except ConnectionError:
            pass

    def close(self) -> None:
        self.dead = True
        try:
            self._sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# replica child entry point
# ---------------------------------------------------------------------------


def serve_replica_main(argv=None) -> None:
    """``python -m theanompi_tpu.serving.replica --spec-json '{...}'``
    — build a checkpoint-restored decoder, host it as a TCP replica,
    print ``REPLICA_READY <port>``, serve until ``shutdown``.

    Spec keys: ``config`` (model dict incl. ``tp``), ``checkpoint``
    (dir), ``paged`` (bool), ``decoder`` (decoder kwargs), ``engine``
    (Engine kwargs), ``name``/``index``, ``host``/``port``,
    ``role`` (``unified``/``prefill``/``decode`` — serving v4),
    ``trace_sample`` (int, 0 = off — span tracing with this replica's
    name as the Perfetto process lane and its role as the thread
    lane; the router stitches the spans it ships back on Results).
    """
    import argparse
    import json

    ap = argparse.ArgumentParser()
    ap.add_argument("--spec-json", required=True)
    args = ap.parse_args(argv)
    spec = json.loads(args.spec_json)

    from theanompi_tpu.serving.decoder import decoder_from_checkpoint
    from theanompi_tpu.utils.recorder import ServingRecorder

    dec = decoder_from_checkpoint(
        dict(spec["config"]), spec["checkpoint"],
        paged=bool(spec.get("paged", False)),
        **dict(spec.get("decoder", {})),
    )
    index = int(spec.get("index", 0))
    tracer = None
    if int(spec.get("trace_sample", 0)) > 0:
        from theanompi_tpu.obs import Tracer

        tracer = Tracer(
            process=spec.get("name", f"replica{index}"),
            lane=spec.get("role", "unified"),
            sample=int(spec["trace_sample"]),
        )
    eng = Engine(
        dec, recorder=ServingRecorder(max_slots=dec.max_slots),
        tracer=tracer,
        **dict(spec.get("engine", {})),
    )
    srv = ReplicaServer(
        eng, name=spec.get("name", f"replica{index}"), index=index,
        host=spec.get("host", "127.0.0.1"),
        port=int(spec.get("port", 0)),
        role=spec.get("role", "unified"),
    ).start()
    print(f"REPLICA_READY {srv.address[1]}", flush=True)
    srv.wait()


if __name__ == "__main__":
    serve_replica_main()
