"""KV-block handoff between replicas — the disaggregated
prefill/decode substrate (serving v4, DistServe/Splitwise's split).

Chunked prefill (serving v2) already isolates the phase boundary:
after the last chunk a request's state is exactly (its prompt's KV
blocks, the first sampled token).  This module turns that state into
a portable HANDOFF RECORD so a PREFILL-SPECIALIST replica can run the
compute-bound phase to completion and ship the result to a
DECODE-SPECIALIST replica, removing prefill chunks from the decode
replica's engine loop entirely (the chunked-prefill TPOT interference
the unified engine merely bounds).

The record is host-side data — plain ints plus per-layer K/V numpy
arrays with the GLOBAL kv-head dim — so it crosses the center-server
pickle frames unchanged, and the tp layout of either side never
appears in it: ``PagedLlamaDecoder.export_blocks`` gathers the head
dim across the sender's shards and ``import_blocks`` re-splits it
over the receiver's (the cross-layout ``model.load`` discipline
applied to KV state), so a prompt prefilled at tp=1 decodes at tp=2
bitwise-identically.

Receive substrate: the decode engine allocates fresh blocks through
its own ``BlockManager`` (table + refcount machinery — a handed-off
request is indistinguishable from a locally-prefilled one once
admitted), scatters the payload in, and seeds the slot directly in
the ``decode`` state with the prefiller's first token.  Only the
prompt's ``blocks_for(n_prompt)`` blocks ship; decode-side growth
allocates the rest as generation crosses block boundaries, exactly
as it does for local requests.

``compatible`` is the loud refusal gate: geometry (layers, kv heads,
head_dim, block size, dtype) must match and the receiver's table must
hold the prompt.  An incompatible or failed handoff never strands the
request — the router drops the record and requeues the FULL prompt
through the ordinary failover path (``finish_reason
"handoff_failed"``), trading the transfer win for availability.
"""

from __future__ import annotations

import numpy as np

HANDOFF_VERSION = 1

#: fields every handoff record carries (the wire contract asserted by
#: ``compatible`` — bump HANDOFF_VERSION when this changes)
HANDOFF_FIELDS = (
    "version", "n_prompt", "first_token", "block_size", "n_blocks",
    "n_layers", "n_kv_heads", "head_dim", "dtype", "layers",
)


def build_handoff(decoder, manager, slot: int, n_prompt: int,
                  first_token: int, trace: dict | None = None) -> dict:
    """Export ``slot``'s prompt KV (the first ``blocks_for(n_prompt)``
    table entries) plus the sampled first token as a portable record.
    Call BEFORE the slot's blocks are freed.  ``trace`` (optional,
    NOT part of the ``compatible`` contract) carries the prefill
    side's span context so a router-less receiver still joins the
    decode leg's spans to the same trace; routed dispatches re-stamp
    ``Request.trace`` anyway."""
    n_blocks = manager.blocks_for(n_prompt)
    bids = manager.slot_blocks(slot, n_blocks)
    return {
        "version": HANDOFF_VERSION,
        "n_prompt": int(n_prompt),
        "first_token": int(first_token),
        "block_size": int(decoder.block_size),
        "n_blocks": int(n_blocks),
        "n_layers": int(decoder.model.n_layers),
        "n_kv_heads": int(decoder.model.n_kv_heads),
        "head_dim": int(decoder.model.head_dim),
        "dtype": str(np.dtype(decoder.pools[0]["k"].dtype)),
        "layers": decoder.export_blocks(bids),
        "trace": dict(trace) if trace is not None else None,
    }


def compatible(decoder, handoff: dict) -> tuple[bool, str]:
    """Can THIS decoder receive ``handoff``?  Returns ``(ok, why)``
    — the engine sheds ``"handoff_failed"`` with ``why`` in the log
    when not, and the router falls back to a full re-prefill."""
    if not getattr(decoder, "paged", False):
        return False, "receiver is not a paged decoder"
    missing = [k for k in HANDOFF_FIELDS if k not in handoff]
    if missing:
        return False, f"handoff record missing {missing}"
    if handoff["version"] != HANDOFF_VERSION:
        return False, (
            f"handoff version {handoff['version']} != "
            f"{HANDOFF_VERSION}"
        )
    m = decoder.model
    geo = {
        "block_size": decoder.block_size,
        "n_layers": m.n_layers,
        "n_kv_heads": m.n_kv_heads,
        "head_dim": m.head_dim,
        "dtype": str(np.dtype(decoder.pools[0]["k"].dtype)),
    }
    for key, want in geo.items():
        if handoff[key] != want:
            return False, (
                f"handoff {key}={handoff[key]!r} != receiver "
                f"{want!r}"
            )
    if handoff["n_blocks"] > decoder.max_blocks:
        return False, (
            f"handoff needs {handoff['n_blocks']} blocks, receiver "
            f"tables hold {decoder.max_blocks}"
        )
    return True, ""


def inject_handoff(decoder, manager, slot: int, handoff: dict) -> None:
    """Receive a handoff into ``slot``: the caller has already
    reserved the table (``manager.assign(slot, [], n_blocks)``); this
    scatters the payload into the receiver's pools at the slot's
    fresh block ids.  After this the slot is exactly what a local
    prefill of the same prompt would have produced."""
    n = handoff["n_blocks"]
    assert manager.n_owned[slot] >= n, (manager.n_owned[slot], n)
    decoder.import_blocks(
        handoff["layers"], manager.slot_blocks(slot, n)
    )


def handoff_bytes(handoff: dict) -> int:
    """Wire size of the record's KV payload (the transfer-cost datum
    the bench reports alongside the TPOT win)."""
    return int(sum(
        np.asarray(lkv["k"]).nbytes + np.asarray(lkv["v"]).nbytes
        for lkv in handoff["layers"]
    ))
