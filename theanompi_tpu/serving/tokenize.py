"""Batched tokenize/detokenize — the serving side of the data plane.

The training loops stage batches through a producer thread so the
device never waits on the host (``data/pipeline.py``); the serving
front door has the same disease one layer up: every ``Engine.submit``
caller that starts from *text* pays a tokenizer call inline on the
submit path, per request, on whatever thread submitted.  Under
concurrent load that is pure serialized host work in front of the
queue — the engine's continuous batching starts only after each
request has been encoded one at a time.

:class:`TokenizeService` moves that work behind a thread + queue with
the same shape as the loader's producer: callers hand a string (or
token ids to detokenize) to the service and get a future; a single
daemon worker drains whatever has accumulated — up to ``max_batch``
items per sweep — encodes the sweep as one batch, and resolves the
futures.  Batching is *natural*: while the worker is busy with one
sweep, new requests pile up and form the next one, so a lone caller
pays no artificial linger (``max_wait_s`` adds one only if asked).

Lock discipline (TM103): futures are resolved and the tokenizer runs
strictly OUTSIDE the condition lock — the lock covers only queue
push/pop, exactly like the engine's submit queue.

Telemetry rides :class:`~theanompi_tpu.utils.recorder.ServingRecorder`
(``record_tokenize``): sweeps, items, tokens, and queue-wait seconds,
so ``summary()``/``metrics_txt`` expose the amortization factor
(items per sweep) next to TTFT — if tokenize wait ever shows up in
the tail, the knob to turn is visible in the same place.

:class:`ByteTokenizer` is the dependency-free codec the tests and
benches use: UTF-8 bytes shifted past the special ids, so any text
round-trips through a 256-entry vocab without an external model file.
"""

from __future__ import annotations

import threading
from collections import deque

__all__ = ["ByteTokenizer", "TokenizeFuture", "TokenizeService"]


class ByteTokenizer:
    """UTF-8 byte-level codec: token id = byte value + ``offset``.

    The offset reserves the low ids for specials (pad/bos/eos) so the
    encoding composes with the synthetic LLaMA vocab; ids below the
    offset decode to nothing (they are control tokens, not text).
    """

    def __init__(self, offset: int = 3):
        self.offset = int(offset)
        if self.offset < 0:
            raise ValueError(f"offset must be >= 0, got {self.offset}")

    @property
    def vocab_size(self) -> int:
        return 256 + self.offset

    def encode(self, text: str) -> list:
        return [b + self.offset for b in text.encode("utf-8")]

    def decode(self, ids) -> str:
        off = self.offset
        bs = bytes(
            i - off for i in ids if off <= int(i) < 256 + off
        )
        return bs.decode("utf-8", errors="replace")

    # batch entry points — what the service's worker calls once per
    # sweep.  For the byte codec these are trivial loops; a real
    # tokenizer amortizes setup/FFI cost here, which is the point of
    # sweeping N requests through one call.
    def encode_batch(self, texts) -> list:
        return [self.encode(t) for t in texts]

    def decode_batch(self, ids_list) -> list:
        return [self.decode(ids) for ids in ids_list]


class TokenizeFuture:
    """Resolution handle for one service item — a minimal future
    (Event + value), resolved by the worker thread OUTSIDE the
    service lock (TM103: no ``._set`` under a lock, no inline
    done-callbacks from a lock holder — this class has none)."""

    def __init__(self):
        self._ev = threading.Event()
        self._value = None
        self._err: BaseException | None = None

    def _resolve(self, value) -> None:
        self._value = value
        self._ev.set()

    def _fail(self, err: BaseException) -> None:
        self._err = err
        self._ev.set()

    def done(self) -> bool:
        return self._ev.is_set()

    def result(self, timeout_s: float | None = None):
        if not self._ev.wait(timeout_s):
            raise TimeoutError("tokenize result not ready")
        if self._err is not None:
            raise self._err
        return self._value


class TokenizeService:
    """Thread + queue batching front-end over a tokenizer.

    ``encode_async``/``decode_async`` enqueue and return a
    :class:`TokenizeFuture`; the blocking wrappers ``tokenize``/
    ``detokenize`` are the submit-path entry (``Engine.submit_text``).
    (Deliberately NOT named ``encode``/``decode``: tmcheck's TM102
    receiver resolution is name-based, and a blocking method named
    like ``str.encode`` would make every ``text.encode()`` call site
    in the tree look like it could reach this wait.)
    One daemon worker sweeps the queue: pop up to ``max_batch`` items
    under the lock, run the tokenizer and resolve futures with the
    lock RELEASED.  ``stop()`` drains what was queued before the stop
    and fails anything submitted after it.
    """

    def __init__(self, tokenizer, *, max_batch: int = 64,
                 max_wait_s: float = 0.0, recorder=None):
        self.tokenizer = tokenizer
        self.max_batch = int(max_batch)
        if self.max_batch < 1:
            raise ValueError(
                f"max_batch must be >= 1, got {self.max_batch}"
            )
        self.max_wait_s = float(max_wait_s)
        self.recorder = recorder
        self._cv = threading.Condition()
        # (kind, payload, enqueue_stamp, future) triples; queue and
        # flags mutate only under _cv (worker + any submitting thread)
        self._q: deque = deque()
        self._stop = False
        self._thread: threading.Thread | None = None
        # exact lifetime counters (worker-thread-owned, folded into
        # the recorder per sweep; read via stats() for tests)
        self.sweeps = 0
        self.items = 0
        self.tokens = 0

    # -- submission (any thread) ------------------------------------------

    def _submit(self, kind: str, payload) -> TokenizeFuture:
        import time

        fut = TokenizeFuture()
        with self._cv:
            if self._stop:
                stopped = True
            else:
                stopped = False
                self._q.append((kind, payload, time.monotonic(), fut))
                self._ensure_thread()
                self._cv.notify()
        if stopped:
            fut._fail(RuntimeError("tokenize service stopped"))
        return fut

    def encode_async(self, text: str) -> TokenizeFuture:
        return self._submit("encode", text)

    def decode_async(self, ids) -> TokenizeFuture:
        return self._submit("decode", list(ids))

    def tokenize(self, text: str, timeout_s: float | None = 30.0):
        return self.encode_async(text).result(timeout_s)

    def detokenize(self, ids, timeout_s: float | None = 30.0) -> str:
        return self.decode_async(ids).result(timeout_s)

    # -- worker -----------------------------------------------------------

    def _ensure_thread(self) -> None:
        # caller holds _cv
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="tm-tokenize", daemon=True
            )
            self._thread.start()

    def _run(self) -> None:
        import time

        while True:
            with self._cv:
                while not self._q and not self._stop:
                    self._cv.wait(0.1)
                if self._stop and not self._q:
                    return
                if (self.max_wait_s > 0.0
                        and len(self._q) < self.max_batch
                        and not self._stop):
                    # optional linger: trade a bounded wait for a
                    # fuller sweep (off by default — natural batching
                    # from worker busy time needs no added latency)
                    self._cv.wait(self.max_wait_s)
                batch = []
                while self._q and len(batch) < self.max_batch:
                    batch.append(self._q.popleft())
            self._sweep(batch, time.monotonic())

    def _sweep(self, batch: list, now: float) -> None:
        """Run one popped sweep and resolve its futures — no lock
        held: the tokenizer call and ``_resolve`` both happen on this
        thread with the queue free to accumulate the next sweep."""
        enc = [(p, f) for k, p, _, f in batch if k == "encode"]
        dec = [(p, f) for k, p, _, f in batch if k == "decode"]
        wait_s = sum(now - t for _, _, t, _ in batch)
        n_tok = 0
        try:
            if enc:
                outs = self.tokenizer.encode_batch([p for p, _ in enc])
                for (_, fut), ids in zip(enc, outs):
                    n_tok += len(ids)
                    fut._resolve(ids)
            if dec:
                outs = self.tokenizer.decode_batch([p for p, _ in dec])
                for (p, fut), text in zip(dec, outs):
                    n_tok += len(p)
                    fut._resolve(text)
        except Exception as e:  # codec bug: fail the sweep, not the thread
            for _, _, _, fut in batch:
                if not fut.done():
                    fut._fail(e)
        self.sweeps += 1
        self.items += len(batch)
        self.tokens += n_tok
        if self.recorder is not None:
            self.recorder.record_tokenize(
                n_items=len(batch), n_tokens=n_tok, wait_s=wait_s
            )

    def stats(self) -> dict:
        return {
            "sweeps": self.sweeps,
            "items": self.items,
            "tokens": self.tokens,
            "items_per_sweep": (
                self.items / self.sweeps if self.sweeps else None
            ),
        }

    def stop(self) -> None:
        """Drain everything queued before the stop, then park the
        worker; post-stop submissions fail fast (their futures
        resolve with an error — never a hang)."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
            t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)
