"""Fleet-scale serving: a front-end router over N engine replicas.

One ``Engine`` is one mesh; heavy traffic needs many.  The
:class:`Router` spreads requests over replicas (in-process or TCP —
``serving/replica.py``) with three pluggable dispatch policies,
health-checked membership, and in-flight failover:

**Policies** (``policy=``):

- ``"round_robin"`` — cycle the healthy members (the baseline).
- ``"least_loaded"`` — min ``replica.load()`` (queue depth + slot
  occupancy, the ``ServingRecorder``-visible load scalar), ties
  broken DETERMINISTICALLY to the lowest member index.
- ``"prefix_affinity"`` — consistent hash on the prompt's
  BLOCK-ALIGNED prefix (``len(prompt) // affinity_block *
  affinity_block`` tokens, at least one block's worth): requests
  sharing a system prompt land on the SAME replica, so its radix
  prefix cache (PR 6) serves them all from one prefill.  The hash
  ring holds every member and the lookup walks it skipping
  unhealthy/backpressured ones, so membership changes only remap
  the keys of the changed member — the consistent-hash stability
  property under test.

**Membership** (supervisor-style, ``utils/supervisor.py`` semantics):
a monitor thread watches each replica's heartbeat; liveness is a
FRESH stamp (never a progress comparison), with ``startup_grace_s``
before the first beat and ``stall_timeout_s`` after.  Stamps land at
engine-ITERATION boundaries, so ``stall_timeout_s`` must exceed the
longest single dispatch a healthy replica performs — in practice the
longest XLA compile (a cold prefill bucket): warm the executables
before registering a replica, or keep the default generous.  A
too-tight timeout is SAFE but wasteful: the "stalled" replica's
requests are requeued (duplicated work, first completion wins) and
it rejoins on its next fresh beat.  A stalled or
dead replica goes UNHEALTHY: its queued and in-flight requests are
requeued to healthy members (dedup on request id + dispatch
generation — a late result from the "dead" replica can never double-
resolve a future, and a requeued duplicate's first completion wins).
Fresh beats from a recovered or relaunched replica REJOIN it
automatically.

**Admission** generalizes the per-request deadline machinery
fleet-wide: ``fleet_queue_cap`` bounds incomplete admitted requests
(shed ``"queue_full"`` at submit past it), ``replica_queue_cap`` is
per-replica backpressure (a saturated member is skipped; if every
member is saturated the request waits at the ROUTER and its deadline
keeps running), and a request whose deadline expires undelivered —
including across requeues, each redispatch carries the REMAINING
budget — sheds ``"deadline"``.  ``max_requeues`` bounds failover
bouncing (then ``"failover"``).  Every ``submit()`` future resolves
with a terminal ``finish_reason``; the engine-level "never hangs"
guarantee extends to the fleet.

**Roles** (serving v4, ``serving/kv_transfer.py``): members declare
``"unified"`` / ``"prefill"`` / ``"decode"``.  When a prefill
specialist AND a decode-capable member are both healthy, a request
dispatches in two phases — the prompt to a prefiller
(``prefill_only``), the returned KV handoff record to the owning
decoder — removing prefill-chunk interference from decode TPOT
(DistServe/Splitwise's split).  Role purity yields to availability:
no healthy specialist → unified members serve end-to-end; a failed
handoff (geometry mismatch, dry pool) drops the record and requeues
the full prompt.  The fleet TTFT for a disaggregated request is the
PREFILL side's (the first token exists at handoff time).

**Scaling** (``serving/autoscaler.py``): ``add_replica`` /
``drain_replica`` / ``remove_replica`` are the control plane's
membership verbs — a drained member takes no new work and its
in-flight requests requeue (uncharged) through the same failover
path, so scale-down never drops a request.

**Observability**: a ``utils.recorder.FleetRecorder`` records every
terminal result router-side (fleet TTFT/TPOT percentiles survive
replica death) plus requeue/failover/rejoin/handoff counters and the
spawn/retire event log (replica-seconds — the autoscaler's cost
metric), and merges per-replica ``ServingRecorder`` states for
occupancy/hit-rate/rate breakdowns (``Router.fleet_summary``).
"""

from __future__ import annotations

import bisect
import hashlib
import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field

import numpy as np

from theanompi_tpu.obs.tracer import Tracer, child_context, force_sample
from theanompi_tpu.serving.engine import (
    Request,
    Result,
    ServingFuture,
)
from theanompi_tpu.utils.recorder import FleetRecorder

#: replica-side shed reasons that mean "this replica abandoned the
#: request without serving it" — the router REQUEUES these instead of
#: propagating them: restart() sheds a dead loop's engine futures, a
#: TCP submit into a dying socket resolves "replica_dead", a stopping
#: replica sheds "shutdown", and an engine whose own queue filled
#: between the router's load probe and the submit sheds "queue_full"
#: (another member probably has room; ``max_requeues`` bounds the
#: bounce either way, ending in a terminal "failover" shed)
_REQUEUE_REASONS = frozenset(
    {"restart", "replica_dead", "shutdown", "queue_full"}
)

POLICIES = ("round_robin", "least_loaded", "prefix_affinity")


def prefix_affinity_key(prompt, block: int) -> bytes:
    """Stable digest of the prompt's block-aligned prefix.  Aligning
    DOWN to the block grid means prompts differing only inside their
    final partial block still share a key — exactly the tokens the
    radix cache can share (block-granularity adoption); prompts
    shorter than one block key on their full length.  sha1, not
    ``hash()``: the mapping must agree across processes and runs."""
    n = len(prompt)
    aligned = max(min(n, block), n // block * block)
    # one buffer, one update — '<i8' pins the byte stream (8-byte
    # little-endian signed, the cross-process contract) regardless
    # of host endianness
    buf = np.asarray(
        list(itertools.islice(prompt, aligned)), dtype="<i8"
    ).tobytes()
    return hashlib.sha1(buf).digest()


class ConsistentHashRing:
    """Classic consistent hashing: each node owns ``n_vnodes``
    pseudo-random points on a 64-bit ring; a key maps to the first
    node point at or after its digest (wrapping).  Removing a node
    only remaps keys that mapped to ITS points; ``lookup`` takes a
    skip-predicate so unhealthy/backpressured nodes are walked past
    without mutating the ring (their keys come back when they do)."""

    def __init__(self, n_vnodes: int = 64):
        self.n_vnodes = int(n_vnodes)
        self._points: list[tuple[int, str]] = []

    def add(self, node: str) -> None:
        for v in range(self.n_vnodes):
            digest = hashlib.sha1(
                f"{node}#{v}".encode()
            ).digest()[:8]
            self._points.append(
                (int.from_bytes(digest, "big"), str(node))
            )
        self._points.sort()

    def remove(self, node: str) -> None:
        self._points = [
            p for p in self._points if p[1] != str(node)
        ]

    def nodes(self) -> set:
        return {n for _, n in self._points}

    def lookup(self, key: bytes, skip=None) -> str | None:
        """First acceptable node clockwise of ``key``'s point (None
        when the ring is empty or everything is skipped)."""
        if not self._points:
            return None
        h = int.from_bytes(hashlib.sha1(key).digest()[:8], "big")
        i = bisect.bisect_left(self._points, (h, ""))
        seen: set = set()
        for off in range(len(self._points)):
            _, node = self._points[(i + off) % len(self._points)]
            if node in seen:
                continue
            seen.add(node)
            if skip is None or not skip(node):
                return node
        return None


@dataclass
class _Member:
    """One replica's membership record.  ``role`` drives the
    disaggregated dispatch (serving v4); ``draining`` marks a
    scale-down victim — it takes no new work while its in-flight
    requests are requeued, and ``remove_replica`` retires it."""

    replica: object
    name: str
    index: int
    role: str = "unified"
    role_pinned: bool = False   # caller-set role: watchdog keeps out
    healthy: bool = True
    draining: bool = False
    seen_beat: bool = False
    last_hb_time: float = 0.0       # the replica's own stamp clock
    last_beat: float = field(default_factory=time.monotonic)


class _FleetEntry:
    __slots__ = (
        "rid", "request", "future", "submit_t", "deadline_s",
        "member", "gen", "n_requeues", "affinity_key", "dispatch_t",
        "handoff", "ttft_prefill", "disagg_ok",
        "ctx", "root", "dspan", "qspan",
    )

    def __init__(self, rid: int, request: Request,
                 deadline_s: float, affinity_key: bytes):
        self.rid = rid
        self.request = request
        self.future = ServingFuture()
        self.submit_t = time.monotonic()
        self.deadline_s = deadline_s
        self.member: _Member | None = None
        self.gen = 0            # dispatch generation (stale-result guard)
        self.n_requeues = 0
        self.affinity_key = affinity_key
        self.dispatch_t: float | None = None
        # disaggregation: the prefill phase's KV record + honest TTFT
        # (the first token exists when PREFILL finishes — the decode
        # replica's own ttft stamp is just its admission time)
        self.handoff: dict | None = None
        self.ttft_prefill: float | None = None
        self.disagg_ok = True   # cleared after a failed handoff
        # tracing (obs/tracer.py): span context, root "request" span,
        # open dispatch-hop span, open router-queue span
        self.ctx: dict | None = None
        self.root: dict | None = None
        self.dspan: dict | None = None
        self.qspan: dict | None = None


class Router:
    """Thread-safe multi-replica front-end; see module docstring."""

    def __init__(
        self,
        replicas=(),
        *,
        policy: str = "least_loaded",
        fleet_queue_cap: int = 256,
        default_deadline_s: float = 60.0,
        replica_queue_cap: int | None = 32,
        stall_timeout_s: float = 30.0,
        startup_grace_s: float = 120.0,
        health_interval_s: float = 0.02,
        affinity_block: int = 16,
        n_vnodes: int = 64,
        max_requeues: int = 3,
        recorder: FleetRecorder | None = None,
        tracer: Tracer | None = None,
        trace_sample: int = 0,
        trace_slo_ttft_s: float | None = None,
        trace_slo_e2e_s: float | None = None,
    ):
        if policy not in POLICIES:
            raise ValueError(
                f"policy must be one of {POLICIES}, got {policy!r}"
            )
        self.policy = policy
        self.fleet_queue_cap = int(fleet_queue_cap)
        self.default_deadline_s = float(default_deadline_s)
        self.replica_queue_cap = (
            None if replica_queue_cap is None else int(replica_queue_cap)
        )
        self.stall_timeout_s = float(stall_timeout_s)
        self.startup_grace_s = float(startup_grace_s)
        self.health_interval_s = float(health_interval_s)
        self.affinity_block = int(affinity_block)
        self.max_requeues = int(max_requeues)
        self.recorder = recorder or FleetRecorder()
        # span tracing (obs/tracer.py): the router owns each
        # request's ROOT span and the per-generation dispatch spans;
        # every Result's replica-side flight record is ingested here,
        # so one connected tree per request survives replica death.
        # Shed / failover / SLO-miss force-sample their traces.
        if tracer is None and int(trace_sample) > 0:
            tracer = Tracer(process="router", sample=int(trace_sample))
        self.tracer = tracer
        self.trace_slo_ttft_s = trace_slo_ttft_s
        self.trace_slo_e2e_s = trace_slo_e2e_s

        self._lock = threading.RLock()
        self._members: list[_Member] = []
        self._ring = ConsistentHashRing(n_vnodes)
        self._pending: dict[int, _FleetEntry] = {}
        self._queue: deque[int] = deque()    # rids awaiting dispatch
        self._rid = itertools.count()
        self._rr = 0
        self._stopping = False
        self._monitor: threading.Thread | None = None
        self._stop = threading.Event()
        for r in replicas:
            self.add_replica(r)

    # -- membership --------------------------------------------------------

    def add_replica(self, replica, name: str | None = None,
                    role: str | None = None) -> str:
        """Register a replica (joins healthy; the watchdog takes it
        from there).  Also the REJOIN path for a replica object the
        caller relaunched under a new identity, and the autoscaler's
        scale-UP hook.  ``role`` defaults to the replica's own
        ``.role`` attribute ("unified" when absent)."""
        with self._lock:
            name = str(
                name if name is not None
                else getattr(replica, "name", f"replica{len(self._members)}")
            )
            if any(m.name == name for m in self._members):
                raise ValueError(f"duplicate replica name {name!r}")
            pinned = role is not None
            role = str(
                role if pinned
                else getattr(replica, "role", "unified")
            )
            self._members.append(
                _Member(replica=replica, name=name,
                        index=len(self._members), role=role,
                        role_pinned=pinned)
            )
            self._ring.add(name)
            self._pump_locked()   # router-held work may fit NOW
            return name

    def drain_replica(self, name: str) -> int:
        """Scale-down drain: the member takes NO new dispatches, and
        its queued + in-flight requests requeue to the rest of the
        fleet through the ordinary failover/dedup path — first
        completion wins, late results from the victim are dropped by
        the generation guard, nothing is lost.  The drain does NOT
        charge the requests' failover budget (being a scale-down
        victim is the fleet's choice, not the request's bad luck).
        Returns how many requests were requeued."""
        with self._lock:
            _, n = self._drain_locked(name)
            self._pump_locked()
            return n

    def _drain_locked(self, name: str) -> tuple[_Member, int]:
        """The ONE copy of drain semantics (shared by drain_replica
        and remove_replica): mark draining, requeue the member's
        pending work uncharged."""
        m = self._member_named(name)
        m.draining = True
        affected = [
            e for e in self._pending.values() if e.member is m
        ]
        self._requeue_locked(affected, charge=False)
        return m, len(affected)

    def remove_replica(self, name: str) -> None:
        """Retire a member (the scale-down endgame, after
        ``drain_replica``): pull its final recorder snapshot into the
        fleet recorder — merged telemetry must conserve its request
        counts after the membership change — then drop it from the
        member list and the hash ring.  Any stragglers still pinned
        to it requeue first (uncharged), so calling this without a
        prior drain is safe too."""
        with self._lock:
            m, _ = self._drain_locked(name)
        try:
            state = m.replica.recorder_state()
            paging = m.replica.paging_stats()
        except Exception:
            pass      # dead/unreachable: keep the last snapshot
        else:
            self.recorder.attach_replica(m.name, state, paging)
        self._salvage_trace(m)   # retired members keep no spans
        with self._lock:
            self._members = [x for x in self._members if x is not m]
            self._ring.remove(name)
            self._pump_locked()

    def _member_named(self, name: str) -> _Member:  # tmcheck: holds=_lock
        m = next(
            (m for m in self._members if m.name == str(name)), None
        )
        if m is None:
            raise KeyError(f"no replica named {name!r}")
        return m

    def members(self) -> dict:
        with self._lock:
            return {
                m.name: {"healthy": m.healthy,
                         "alive": m.replica.alive(),
                         "role": m.role,
                         "draining": m.draining}
                for m in self._members
            }

    def _healthy(self) -> list[_Member]:  # tmcheck: holds=_lock
        return [m for m in self._members if m.healthy]

    def _dispatchable(self) -> list[_Member]:  # tmcheck: holds=_lock
        return [
            m for m in self._members if m.healthy and not m.draining
        ]

    # -- admission (any thread) --------------------------------------------

    def submit(self, prompt, **kw) -> ServingFuture:
        """Queue one request on the fleet; the future ALWAYS resolves
        (served by some replica, or shed with a reason)."""
        if isinstance(prompt, Request):
            if kw:
                raise TypeError(
                    f"submit(Request, ...) does not accept keyword "
                    f"overrides {sorted(kw)} — set them on the "
                    f"Request itself"
                )
            req = prompt
        else:
            req = Request(prompt=list(prompt), **kw)
        deadline = (
            req.deadline_s if req.deadline_s is not None
            else self.default_deadline_s
        )
        entry = _FleetEntry(
            next(self._rid), req, deadline,
            # only the affinity policy reads the key — don't pay a
            # sha1 over a 2k-token prompt on every least_loaded/
            # round_robin submit
            prefix_affinity_key(req.prompt, self.affinity_block)
            if self.policy == "prefix_affinity" else b"",
        )
        if self.tracer is not None:
            entry.ctx = self.tracer.new_context()
            entry.root = self.tracer.start_span(
                entry.ctx, "request", n_prompt=len(req.prompt)
            )
            # callers (and the critical_path report) find the trace
            # through the future they already hold
            entry.future.trace_id = entry.ctx["trace_id"]
        with self._lock:
            if self._stopping:
                reason = "shutdown"
            elif len(self._pending) >= self.fleet_queue_cap:
                reason = "queue_full"
            else:
                reason = None
                self._pending[entry.rid] = entry
                if self._queue:
                    # FIFO fairness: older router-held requests (back-
                    # pressured or failover-requeued) get first claim
                    # on any freed capacity — a fresh submit must not
                    # race past them to a slot and starve them to
                    # "deadline"
                    self._enqueue_locked(entry)
                    self._pump_locked()
                elif not self._try_dispatch(entry):
                    self._enqueue_locked(entry)
        if reason is not None:
            # admission sheds resolve OUTSIDE the lock (same shape as
            # Engine.submit): the entry was never published, so only
            # this thread can resolve it, and the caller's future
            # callbacks never run under the router lock
            return self._shed(entry, reason)
        return entry.future

    def _root_id(self, entry: _FleetEntry) -> int | None:
        return entry.root["span_id"] if entry.root is not None else None

    def _enqueue_locked(self, entry: _FleetEntry) -> None:  # tmcheck: holds=_lock
        """Queue a router-held entry, opening its router_queue span
        (the named leg the critical path shows for backpressure)."""
        if (self.tracer is not None and entry.ctx is not None
                and entry.qspan is None):
            entry.qspan = self.tracer.start_span(
                entry.ctx, "router_queue",
                parent_id=self._root_id(entry),
            )
        self._queue.append(entry.rid)

    def _shed(self, entry: _FleetEntry, reason: str) -> ServingFuture:
        now = time.monotonic()
        if self.tracer is not None and entry.ctx is not None:
            # a shed is exactly the tail 1/N sampling must not lose
            force_sample(entry.ctx)
            self.tracer.end_span(entry.qspan, reason=reason)
            self.tracer.end_span(entry.dspan, outcome=reason)
            entry.qspan = entry.dspan = None
            self.tracer.end_span(entry.root, status="shed",
                                 finish_reason=reason)
            entry.root = None
        entry.future._set(Result(
            status="shed", finish_reason=reason,
            queued_s=now - entry.submit_t,
        ))
        self.recorder.record_request(
            status="shed", finish_reason=reason,
            n_prompt=len(entry.request.prompt), n_generated=0,
            queued_s=now - entry.submit_t,
        )
        return entry.future

    # -- dispatch (lock held) ----------------------------------------------

    def _over_cap(self, m: _Member) -> bool:
        return (
            self.replica_queue_cap is not None
            and m.replica.load() >= self.replica_queue_cap
        )

    def _candidates(  # tmcheck: holds=_lock
        self, entry: _FleetEntry
    ) -> tuple[list[_Member], str]:
        """Role-aware candidate set + dispatch mode for one entry
        (serving v4).  Modes: ``"prefill"`` (send the prompt to a
        prefill specialist, expect a handoff back), ``"decode"``
        (carry the handoff to a decode-capable member), ``"unified"``
        (serve end-to-end).  Role purity yields to availability at
        every step — when no specialist is healthy the request falls
        back to unified members, and when ONLY specialists are
        healthy they serve outside their specialty rather than
        starve the request."""
        avail = self._dispatchable()
        if not avail:
            return [], "unified"
        pre = [m for m in avail if m.role == "prefill"]
        dec = [m for m in avail if m.role == "decode"]
        uni = [m for m in avail if m.role == "unified"]
        if entry.handoff is not None:
            return (dec or uni or avail), "decode"
        if (pre and (dec or uni) and entry.disagg_ok
                and entry.request.max_tokens > 1):
            # disaggregate: prefill somewhere that can hand off, and
            # someone else can decode.  max_tokens<=1 requests have
            # nothing to decode — a handoff would be pure overhead.
            return pre, "prefill"
        return (uni or avail), "unified"

    def _choose(self, entry: _FleetEntry,  # tmcheck: holds=_lock
                healthy: list[_Member]) -> _Member | None:
        if not healthy:
            return None
        if self.policy == "prefix_affinity":
            by_name = {m.name: m for m in healthy}
            name = self._ring.lookup(
                entry.affinity_key,
                skip=lambda n: (
                    n not in by_name or self._over_cap(by_name[n])
                ),
            )
            return by_name.get(name) if name is not None else None
        if self.policy == "least_loaded":
            # one load() probe per member: a consistent snapshot for
            # both the cap filter and the pick (load() is a lock +
            # possibly a wire-cache read on TCP replicas)
            loads = [(m.replica.load(), m.index, m) for m in healthy]
            free = [
                t for t in loads
                if self.replica_queue_cap is None
                or t[0] < self.replica_queue_cap
            ]
            if not free:
                return None
            # deterministic tie-break: (load, member index)
            return min(free, key=lambda t: t[:2])[2]
        # round_robin: advance the cursor past unhealthy/saturated
        for _ in range(len(healthy)):
            m = healthy[self._rr % len(healthy)]
            self._rr += 1
            if not self._over_cap(m):
                return m
        return None

    def _try_dispatch(self, entry: _FleetEntry) -> bool:  # tmcheck: holds=_lock
        """Dispatch one pending entry if a member will take it; the
        caller holds the lock.  Expired entries shed here (the
        deadline generalizes across requeues: each redispatch carries
        only the REMAINING budget)."""
        now = time.monotonic()
        remaining = entry.deadline_s - (now - entry.submit_t)
        if remaining <= 0:
            del self._pending[entry.rid]
            # deliberate resolve-under-RLock: deadline expiry is
            # found mid-dispatch, and deferring it would let the dead
            # entry be re-dispatched first.  User callbacks run under
            # the router RLock (re-entry is safe; callbacks must not
            # take foreign locks — docs/ANALYSIS.md TM103).
            self._shed(entry, "deadline")  # tmcheck: disable=TM103
            return True      # terminal — no longer queued
        candidates, mode = self._candidates(entry)
        member = self._choose(entry, candidates)
        if member is None and mode != "unified":
            # role purity yields to availability for LOAD too, not
            # just health: a saturated/backpressured specialist pool
            # must not hold a request at the router while non-
            # specialist members sit idle — a prefill-phase request
            # serves end-to-end instead, a decode-phase handoff goes
            # to any member (the engine underneath is identical)
            rest = [
                m for m in self._dispatchable()
                if m not in candidates
            ]
            member = self._choose(entry, rest)
            if member is not None and mode == "prefill":
                mode = "unified"
        if member is None:
            return False
        entry.gen += 1
        entry.member = member
        entry.dispatch_t = now
        gen = entry.gen
        req = entry.request
        trace_ctx = None
        if self.tracer is not None and entry.ctx is not None:
            self.tracer.end_span(entry.qspan)
            entry.qspan = None
            entry.dspan = self.tracer.start_span(
                entry.ctx, "dispatch", parent_id=self._root_id(entry),
                member=member.name, mode=mode, gen=gen,
            )
            # the replica's spans parent under THIS dispatch hop —
            # the context (incl. the sampled bit) rides the Request
            # across the TCP frames unchanged
            trace_ctx = child_context(
                entry.ctx, entry.dspan["span_id"]
            )
        efut = member.replica.submit(Request(
            prompt=list(req.prompt), max_tokens=req.max_tokens,
            temperature=req.temperature, deadline_s=remaining,
            seed=req.seed,
            prefill_only=(mode == "prefill"),
            handoff=entry.handoff,
            trace=trace_ctx,
        ))
        self.recorder.record_dispatch(member.name)
        # deliberate register-under-RLock: an already-resolved efut
        # fires _on_result inline on THIS thread, which re-enters the
        # RLock; registering outside the lock would open a window
        # where a racing requeue misses the generation bump.
        efut.add_done_callback(  # tmcheck: disable=TM103
            lambda res, rid=entry.rid, gen=gen:
                self._on_result(rid, gen, res)
        )
        return True

    # -- completion (replica threads) --------------------------------------

    def _on_result(self, rid: int, gen: int, res: Result) -> None:
        if self.tracer is not None and res.spans:
            # the replica-side flight record — ingested for EVERY
            # delivery (stale/duplicate results are real duplicated
            # work on the same tree; span-id dedup handles replays)
            self.tracer.ingest(res.spans)
        with self._lock:
            entry = self._pending.get(rid)
            if entry is None or entry.gen != gen:
                return    # stale: requeued elsewhere / double-resolve
            if (
                res.status == "ok"
                and res.finish_reason == "prefilled"
                and res.handoff is not None
            ):
                # phase boundary (serving v4): the prefill specialist
                # returned the KV record — carry it to a decode
                # member.  NOT a terminal result: the user future
                # stays pending and nothing is recorded yet.  The
                # honest fleet TTFT is the PREFILL side's (the first
                # token exists now).
                shift = (
                    entry.dispatch_t - entry.submit_t
                    if entry.dispatch_t is not None else 0.0
                )
                entry.handoff = res.handoff
                if res.ttft_s is not None:
                    entry.ttft_prefill = res.ttft_s + shift
                entry.gen += 1        # invalidate the prefill hop
                entry.member = None
                self.recorder.record_handoff()
                if self.tracer is not None and entry.ctx is not None:
                    self.tracer.end_span(entry.dspan,
                                         outcome="prefilled")
                    entry.dspan = None
                    t = self.tracer.clock()
                    self.tracer.record_span(
                        entry.ctx, "handoff", t, t,
                        parent_id=self._root_id(entry),
                        n_blocks=res.handoff.get("n_blocks"),
                    )
                if self._queue:
                    # FIFO fairness, same as submit()
                    self._enqueue_locked(entry)
                    self._pump_locked()
                elif not self._try_dispatch(entry):
                    self._enqueue_locked(entry)
                return
            if (
                res.status == "shed"
                and entry.handoff is not None
                and res.finish_reason in ("handoff_failed", "no_blocks")
            ):
                # the receiver couldn't take the handoff (geometry
                # mismatch, dry pool): drop the record and retry the
                # FULL prompt end-to-end — the transfer is an
                # optimization, the request must never die with it
                # (disagg_ok stops the retry from re-disaggregating
                # into the same failure)
                entry.handoff = None
                entry.ttft_prefill = None
                entry.disagg_ok = False
                self._requeue_locked([entry])
                return
            if (
                res.status == "shed"
                and res.finish_reason in _REQUEUE_REASONS
            ):
                # the replica abandoned it without serving: failover
                self._requeue_locked([entry])
                return
            del self._pending[rid]
            if rid in self._queue:      # paranoia; dispatched rids
                self._queue.remove(rid)  # are not queued
        # re-base the latency fields on the ROUTER submit time — the
        # replica measured from ITS OWN admission, which for a
        # requeued or router-held request understates the wait
        shift = (
            entry.dispatch_t - entry.submit_t
            if entry.dispatch_t is not None else 0.0
        )
        ttft = (
            entry.ttft_prefill if entry.ttft_prefill is not None
            else res.ttft_s + shift if res.ttft_s is not None
            else None
        )
        out = Result(
            status=res.status, finish_reason=res.finish_reason,
            tokens=list(res.tokens),
            ttft_s=ttft,
            tpot_s=res.tpot_s,
            queued_s=(
                res.queued_s + shift
                if res.queued_s is not None else shift
            ),
            e2e_s=(
                res.e2e_s + shift if res.e2e_s is not None else None
            ),
        )
        if self.tracer is not None and entry.ctx is not None:
            slo_miss = (
                (self.trace_slo_ttft_s is not None
                 and out.ttft_s is not None
                 and out.ttft_s > self.trace_slo_ttft_s)
                or (self.trace_slo_e2e_s is not None
                    and out.e2e_s is not None
                    and out.e2e_s > self.trace_slo_e2e_s)
            )
            if out.status == "shed" or slo_miss:
                # keep the interesting tail — forced BEFORE the
                # still-open dispatch span ends, so the kept trace
                # carries its member/mode leg, not just the root
                force_sample(entry.ctx)
            self.tracer.end_span(entry.dspan,
                                 outcome=out.finish_reason)
            entry.dspan = None
            self.tracer.end_span(
                entry.root, status=out.status,
                finish_reason=out.finish_reason, slo_miss=slo_miss,
            )
            entry.root = None
        entry.future._set(out)
        self.recorder.record_request(
            status=out.status, finish_reason=out.finish_reason,
            n_prompt=len(entry.request.prompt),
            n_generated=len(out.tokens),
            ttft_s=out.ttft_s, tpot_s=out.tpot_s,
            queued_s=out.queued_s, e2e_s=out.e2e_s,
        )

    # -- failover ----------------------------------------------------------

    def _requeue_locked(self, entries: list, charge: bool = True) -> None:
        """``charge=False`` (scale-down drains) requeues without
        spending the entries' failover budget: the fleet chose to
        move them, so bouncing between drained victims must never
        shed a request "failover"."""
        n = 0
        for entry in entries:
            entry.gen += 1        # invalidate in-flight callbacks
            entry.member = None
            if self.tracer is not None and entry.ctx is not None:
                # failover is an always-sample event: the forced bit
                # rides every later dispatch, so the retry legs are
                # fully traced even at 1/N
                force_sample(entry.ctx)
                self.tracer.end_span(entry.dspan, outcome="requeue")
                entry.dspan = None
                t = self.tracer.clock()
                self.tracer.record_span(
                    entry.ctx, "requeue", t, t,
                    parent_id=self._root_id(entry),
                    gen=entry.gen, charged=charge,
                )
            if charge:
                if entry.n_requeues >= self.max_requeues:
                    del self._pending[entry.rid]
                    # deliberate resolve-under-RLock: the failover
                    # budget is spent mid-sweep; see _try_dispatch's
                    # deadline shed for the rationale
                    self._shed(entry, "failover")  # tmcheck: disable=TM103
                    continue
                entry.n_requeues += 1
            self._enqueue_locked(entry)
            n += 1
        if n:
            self.recorder.record_requeue(n)

    def _fail_member(self, member: _Member, cause: str) -> None:
        with self._lock:
            if not member.healthy:
                return
            member.healthy = False
            self.recorder.record_failover(member.name)
            affected = [
                e for e in self._pending.values()
                if e.member is member
            ]
            self._requeue_locked(affected)
        # pull the flight recorder from the wreck: a replica whose
        # LOOP died (fault drill, crash) often still answers its
        # wire/object, so the spans of the requests it was serving —
        # which never got a Result to ride — survive into the
        # router's ring.  Best-effort, OUTSIDE the lock (wire call).
        self._salvage_trace(member)

    def _salvage_trace(self, member: _Member) -> None:
        if self.tracer is None:
            return
        fn = getattr(member.replica, "trace_state", None)
        if fn is None:
            return
        try:
            spans = fn()
        except Exception:
            return      # truly gone: its unsent spans die with it
        if spans:
            self.tracer.ingest(spans)

    # -- health monitor ----------------------------------------------------

    def start(self) -> "Router":
        if self._monitor is not None:
            raise RuntimeError("router already started")
        self._stop.clear()
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="tm-fleet-monitor",
            daemon=True,
        )
        self._monitor.start()
        return self

    def check_health(self) -> None:
        """One watchdog pass (the monitor thread calls this every
        ``health_interval_s``; tests may call it directly).
        Liveness = a FRESH heartbeat stamp — supervisor semantics:
        progress counters rewind on restart, fresh writes don't."""
        now = time.monotonic()
        with self._lock:
            members = list(self._members)
        for m in members:
            hb = m.replica.heartbeat()
            alive = m.replica.alive()
            # converge the dispatch role with the replica's own: a
            # TCP client registered before its first pong reported
            # the caller's default, and the pong's correction must
            # reach _candidates(), not just the client object.  A
            # role the caller EXPLICITLY passed to add_replica is
            # pinned — the watchdog must not revert that override.
            role = getattr(m.replica, "role", None)
            if not m.role_pinned and role is not None \
                    and role != m.role:
                m.role = role
            if hb.get("time", 0.0) > m.last_hb_time and alive:
                m.last_hb_time = hb["time"]
                m.last_beat = now
                m.seen_beat = True
                if not m.healthy:
                    with self._lock:
                        m.healthy = True
                    self.recorder.record_rejoin(m.name)
            if not m.healthy:
                continue
            limit = (
                self.stall_timeout_s if m.seen_beat
                else self.startup_grace_s
            )
            if not alive:
                self._fail_member(m, "dead")
            elif now - m.last_beat > limit:
                self._fail_member(m, "stall")

    def _pump_queue(self) -> None:
        """Retry dispatch for router-held requests (backpressure
        cleared, a member rejoined, or a deadline expired)."""
        with self._lock:
            self._pump_locked()

    def _pump_locked(self) -> None:
        rids = list(self._queue)
        self._queue.clear()
        for rid in rids:
            entry = self._pending.get(rid)
            if entry is None:
                continue
            if not self._try_dispatch(entry):
                self._queue.append(rid)

    def _monitor_loop(self) -> None:
        while not self._stop.is_set():
            self.check_health()
            self._pump_queue()
            time.sleep(self.health_interval_s)

    # -- shutdown / observability ------------------------------------------

    def drain(self, timeout: float = 300.0) -> bool:
        """Block until every admitted request has resolved (True) or
        the timeout passes (False) — the closed-loop bench idiom."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if not self._pending:
                    return True
            if self._monitor is None:
                # inline mode: no monitor thread pumping for us
                self.check_health()
                self._pump_queue()
            time.sleep(self.health_interval_s)
        with self._lock:
            return not self._pending

    def stop(self, drain_s: float = 30.0) -> None:
        """Refuse new admissions, give in-flight work ``drain_s`` to
        finish, then shed the stragglers ("shutdown") — every future
        still resolves.  Replica lifecycles belong to the caller."""
        with self._lock:
            self._stopping = True
        self.drain(timeout=drain_s)
        with self._lock:
            leftovers = list(self._pending.values())
            self._pending.clear()
            self._queue.clear()
        for entry in leftovers:
            entry.gen += 1   # silence any late replica callbacks
            self._shed(entry, "shutdown")
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=30.0)
            self._monitor = None

    def pending(self) -> int:
        with self._lock:
            return len(self._pending)

    def fleet_capacity(self, default_slots: int = 1) -> int:
        """Total decode-slot capacity of the dispatchable (healthy,
        non-draining) members — the autoscaler's pressure
        denominator.  Replicas without a ``slots()`` probe count as
        ``default_slots``."""
        with self._lock:
            members = self._dispatchable()
        total = 0
        for m in members:
            fn = getattr(m.replica, "slots", None)
            total += int(fn()) if callable(fn) else int(default_slots)
        return total

    def member_loads(self) -> dict:
        """Per-member ``load()`` snapshot of dispatchable members —
        the autoscaler's victim-selection input."""
        with self._lock:
            members = self._dispatchable()
        return {m.name: m.replica.load() for m in members}

    def replica_named(self, name: str):
        """The replica object behind a member (the autoscaler's
        retire hook needs it after ``remove_replica`` forgets it)."""
        with self._lock:
            return self._member_named(name).replica

    def refresh_replica_stats(self) -> None:
        """Pull each reachable replica's recorder state (and paging
        stats) into the fleet recorder — call before
        ``fleet_summary`` (unreachable replicas keep their last
        attached snapshot; their completions were recorded
        router-side anyway)."""
        with self._lock:
            members = list(self._members)
        for m in members:
            try:
                state = m.replica.recorder_state()
                paging = m.replica.paging_stats()
            except Exception:
                continue   # dead/unreachable: keep the last snapshot
            self.recorder.attach_replica(m.name, state, paging)

    def fleet_summary(self) -> dict:
        self.refresh_replica_stats()
        out = self.recorder.summary()
        out["members"] = self.members()
        out["policy"] = self.policy
        return out

    def metrics_txt(self) -> str:
        """Prometheus-style text for the whole fleet, on demand —
        pulls fresh replica recorder states first (no HTTP server;
        dump it wherever the scrape lives)."""
        self.refresh_replica_stats()
        return self.recorder.metrics_txt()

    # -- tracing (obs/) ----------------------------------------------------

    def collect_spans(self, trace_id: int | None = None) -> list:
        """Router-ring spans, after best-effort pulls of every
        reachable replica's flight recorder (covers traces still in
        flight; completed requests' spans already rode their
        Results).  Wire calls happen OUTSIDE the router lock."""
        if self.tracer is None:
            return []
        with self._lock:
            members = list(self._members)
        for m in members:
            self._salvage_trace(m)
        return self.tracer.spans(trace_id)

    def critical_path(self, trace_id: int) -> dict | None:
        """The "why was this request slow" report (obs/export.py):
        the longest serial chain with per-leg durations, from the
        router's stitched tree.  ``trace_id`` comes from the
        submitted future's ``trace_id`` attribute.  Returns ``None``
        when the ring holds no spans for that trace — at 1/N
        sampling that is most requests (unsampled and uneventful:
        shed/failover/SLO-miss traces are always kept, and
        ``trace_sample=1`` keeps everything)."""
        from theanompi_tpu.obs import export

        if self.tracer is None:
            return None
        spans = self.tracer.spans(trace_id)
        if not spans:
            return None
        return export.critical_path(spans, trace_id)

    def export_trace(self, path, trace_id: int | None = None) -> str:
        """Write the Perfetto-openable Chrome-trace JSON for one
        trace (or everything in the ring) to ``path``."""
        from theanompi_tpu.obs import export

        return export.write_chrome_trace(
            self.collect_spans(trace_id), path, trace_id=trace_id
        )
