"""Fused gather+attend Pallas kernel for paged KV-cache decode
(serving speed-of-light, ROADMAP item 1b).

The jnp gather path (``PagedLlamaDecoder._gather_kv``) materializes
the block-table read as a ``[S, Hkv, MB*bs, hd]`` tensor per layer —
PR 6's decode-cost attribution (``paged_attend_frac`` in the
``serving_paged`` bench row) puts most of decode time there, and on
real hardware that tensor is an HBM round trip: the pool rows are
READ, WRITTEN back as the gathered copy, and READ again by the
attention matmuls (~3x the padded window's bytes).  This kernel fuses
the walk: each grid cell (slot, kv-head) DMAs its slot's blocks from
the HBM pools straight into contiguous VMEM scratch — the gathered
history never exists in HBM — and computes the attention against it
in place.  KV bytes move once, at the fused arithmetic intensity
``serving_roofline`` models (``paged_attend_intensity``).

Exactness contract: the kernel mirrors the gather oracle's op
sequence exactly — same einsum contractions, same ``astype(f32) *
hd**-0.5`` scale, same ``where(pos-mask, ·, NEG_INF)`` +
``jax.nn.softmax`` — so for fp32 pools the outputs are BITWISE equal
to the gather path (tests/test_paged_attention.py asserts exact
equality across block-boundary, ragged-length and trash-padding
cases).  That makes the gather path the kernel's reference oracle:
``interpret=True`` runs the kernel through the Pallas interpreter on
this CPU image (testable here), and the same code compiles through
Mosaic on a real TPU unchanged (``interpret=False`` — the decoder
flips it by backend).

Shapes (all per tp shard — the decoder calls this inside shard_map,
so ``hkv``/``rep`` are the LOCAL head counts):

- ``q``      ``[S, Q, Hkv, rep, hd]`` — Q query rows per slot (1 for
  plain decode, ``k`` for a speculative verify step);
- ``k_pool``/``v_pool`` ``[n_blocks + 1, Hkv, bs, hd]`` (last row =
  trash block);
- ``tables`` ``[S, MB]`` int32 (trash-padded past the owned prefix);
- ``pos``    ``[S, Q]`` int32 — row (s, q) attends positions
  ``<= pos[s, q]``.

Table entries and positions are SCALAR-PREFETCH arguments
(``PrefetchScalarGridSpec``): the block ids must be known before the
kernel body runs to program the DMAs.  Trash-padded table entries are
walked too — their positions sit past every ``pos``, so the mask
kills them (the same branch-free discipline as the gather path).

VMEM budget per grid cell: ``2 * MB * bs * hd * itemsize`` for the
K/V scratch (e.g. 4 MiB at ctx 8192, hd 128, bf16) — within the
~16 MiB/core budget for serving-sized contexts; longer contexts want
a second grid axis over the window, which changes the softmax
association and therefore the exactness bar (documented, not built).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from theanompi_tpu.ops.attention import NEG_INF

IMPLS = ("gather", "pallas")


def _paged_attend_kernel(tables_ref, pos_ref, q_ref, kp_ref, vp_ref,
                         o_ref, ks, vs, ksem, vsem, *,
                         mb: int, bs: int, nq: int, scale: float):
    """One (slot, kv-head) cell: DMA the slot's ``mb`` blocks into
    contiguous VMEM, then attend each of the ``nq`` query rows
    against the gathered window under its own position mask."""
    s = pl.program_id(0)
    h = pl.program_id(1)

    def block_dma(b, bid):
        return (
            pltpu.make_async_copy(
                kp_ref.at[bid, h], ks.at[pl.ds(b * bs, bs)], ksem.at[b]
            ),
            pltpu.make_async_copy(
                vp_ref.at[bid, h], vs.at[pl.ds(b * bs, bs)], vsem.at[b]
            ),
        )

    # the block-table walk: start every block's K and V copy (the DMA
    # engines pipeline them), then wait once per block
    for b in range(mb):
        for dma in block_dma(b, tables_ref[s, b]):
            dma.start()
    for b in range(mb):
        for dma in block_dma(b, tables_ref[s, b]):
            dma.wait()

    kg = ks[:]                                   # [MB*bs, hd]
    vg = vs[:]
    # EXACTLY the gather oracle's op sequence (decoder
    # `paged_attend` scope): einsum in compute dtype over ALL query
    # rows at once (so the matmul's row count matches the oracle's
    # per-(slot, head) row group — XLA's matvec lowering is row-count
    # sensitive), f32 cast, scale, per-row position mask, softmax,
    # then prob-weighted V as mult+reduce (NOT a dot_general): reduce
    # lowering is association-stable across batching, matmul is not.
    # The fp32-bitwise-equality contract with the gather path lives
    # here; decoder._paged_attend documents the other half.
    rep = q_ref.shape[3]
    q2 = q_ref[0, :, 0].reshape(nq * rep, -1)    # [nq*rep, hd]
    sc = jnp.einsum("rd,td->rt", q2, kg).astype(jnp.float32) * scale
    t_idx = jax.lax.broadcasted_iota(jnp.int32, sc.shape, 1)
    pos_col = jnp.concatenate(
        [jnp.full((rep, 1), pos_ref[s, j], jnp.int32)
         for j in range(nq)], axis=0,
    )                                            # [nq*rep, 1]
    sc = jnp.where(t_idx <= pos_col, sc, NEG_INF)
    probs = jax.nn.softmax(sc, axis=-1)
    o = jnp.sum(
        probs.astype(vg.dtype)[..., None] * vg[None, :, :], axis=-2
    )                                            # [nq*rep, hd]
    o_ref[0, :, 0] = o.reshape(nq, rep, -1)


def paged_attend(q, k_pool, v_pool, tables, pos, *,
                 interpret: bool = True):
    """Fused block-table attention: ``q`` [S, Q, Hkv, rep, hd] against
    the paged pools through ``tables`` [S, MB] with per-row position
    masks ``pos`` [S, Q].  Returns [S, Q, Hkv, rep, hd] in the pool
    dtype — bitwise-equal to the decoder's gather path for fp32."""
    s, nq, hkv, rep, hd = q.shape
    nb1, hkv_p, bs, hd_p = k_pool.shape
    assert (hkv, hd) == (hkv_p, hd_p), (q.shape, k_pool.shape)
    assert k_pool.shape == v_pool.shape
    mb = tables.shape[1]
    assert tables.shape == (s, mb) and pos.shape == (s, nq), (
        tables.shape, pos.shape, q.shape
    )
    t_pad = mb * bs

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,                   # tables, pos
        grid=(s, hkv),
        in_specs=[
            pl.BlockSpec(
                (1, nq, 1, rep, hd), lambda i, j, *_: (i, 0, j, 0, 0)
            ),
            pl.BlockSpec(memory_space=pltpu.ANY),   # K pool stays HBM
            pl.BlockSpec(memory_space=pltpu.ANY),   # V pool stays HBM
        ],
        out_specs=pl.BlockSpec(
            (1, nq, 1, rep, hd), lambda i, j, *_: (i, 0, j, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((t_pad, hd), k_pool.dtype),
            pltpu.VMEM((t_pad, hd), v_pool.dtype),
            pltpu.SemaphoreType.DMA((mb,)),
            pltpu.SemaphoreType.DMA((mb,)),
        ],
    )
    kernel = functools.partial(
        _paged_attend_kernel, mb=mb, bs=bs, nq=nq, scale=hd ** -0.5
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, v_pool.dtype),
        interpret=interpret,
    )(tables, pos, q, k_pool, v_pool)
