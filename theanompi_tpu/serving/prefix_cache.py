"""Radix prefix cache over paged KV blocks (serving v2).

The dominant pattern when millions of users hit one deployment is a
SHARED SYSTEM PROMPT: thousands of requests whose token streams agree
on a long prefix.  The v1 engine re-prefilled that prefix per request.
This cache (the SGLang RadixAttention idea, adapted to block-granular
paging) maps token-id prefixes → the physical KV blocks that already
hold their computed K/V, so a new request ADOPTS the prefix blocks
(refcount bump, zero prefill compute) and only prefills its divergent
suffix.

Structure: a block-granularity trie.  Each node covers up to
``block_size`` consecutive tokens and owns one reference on one
physical block; children of a FULL node are keyed by their exact
token tuple.  Matching walks exact full-block children greedily, then
takes the best common prefix against one more child (full or
partial) — adopting a block mid-way is safe because the adopter's
first write into it passes the ``BlockManager.ensure_writable``
copy-on-write gate.  Partial tails with different tokens coexist as
sibling leaves (a true radix would merge them; duplication is bounded
by LRU eviction and keeps insert/match branch-free).

Eviction is leaf-only and LRU by a deterministic logical clock: only
nodes whose block has refcount 1 (held ONLY by the cache) are
evictable — evicting a block a live slot still reads would corrupt
it.  ``evict(n)`` is what the engine calls when the allocator runs
dry, before declaring ``no_blocks``.

All bitwise guarantees survive adoption: K/V rows are a per-row
function of the token prefix and absolute position only (row-wise
matmuls, per-position RoPE), so an adopted block holds bit-identical
content to what a cold prefill of the same tokens would write —
``tests/test_serving_paged.py`` pins hit-vs-cold token equality.
"""

from __future__ import annotations

from theanompi_tpu.serving.blocks import BlockAllocator


class _Node:
    __slots__ = (
        "tokens", "n_valid", "block", "children", "parent", "last_used",
    )

    def __init__(self, tokens: tuple, block: int | None, parent):
        self.tokens = tokens          # the token ids this block covers
        self.n_valid = len(tokens)    # == block_size for full nodes
        self.block = block            # physical block id (root: None)
        self.children: dict[tuple, _Node] = {}
        self.parent = parent
        self.last_used = 0


class PrefixCache:
    """Block-granularity radix/trie prefix cache over one allocator.

    The cache holds ONE reference per cached block; ``match`` hands
    the caller one more reference per returned block (the caller —
    the slot table — owns releasing it).
    """

    def __init__(self, allocator: BlockAllocator):
        self.allocator = allocator
        self.block_size = allocator.block_size
        self._root = _Node((), None, None)
        self._clock = 0               # logical LRU clock: deterministic
        self.n_lookups = 0
        self.n_hits = 0               # lookups that matched > 0 tokens
        self.matched_tokens = 0
        self.inserted_blocks = 0
        self.evicted_blocks = 0

    # -- introspection -----------------------------------------------------

    def n_nodes(self) -> int:
        def count(node: _Node) -> int:
            return 1 + sum(count(c) for c in node.children.values())

        return count(self._root) - 1   # root holds no block

    def stats(self) -> dict:
        return {
            "n_nodes": self.n_nodes(),
            "n_lookups": self.n_lookups,
            "n_hits": self.n_hits,
            "matched_tokens": self.matched_tokens,
            "inserted_blocks": self.inserted_blocks,
            "evicted_blocks": self.evicted_blocks,
        }

    # -- core operations ---------------------------------------------------

    def match(self, tokens, max_len: int | None = None):
        """Longest cached prefix of ``tokens``, capped at ``max_len``
        (the engine passes ``len(prompt) - 1`` so at least one prompt
        token is always prefilled — its logits seed the first sampled
        token).  Returns ``(matched_len, block_ids)`` where
        ``block_ids`` covers ``ceil(matched_len / block_size)``
        blocks, each with ONE reference taken for the caller."""
        bs = self.block_size
        limit = len(tokens) if max_len is None else min(
            max_len, len(tokens)
        )
        self._clock += 1
        self.n_lookups += 1
        node = self._root
        matched = 0
        blocks: list[int] = []
        while matched < limit:
            rem = tuple(tokens[matched: matched + bs])
            # a full remaining window can walk an exact full child
            if len(rem) == bs and limit - matched >= bs:
                child = node.children.get(rem)
                if child is not None and child.n_valid == bs:
                    self.allocator.ref(child.block)
                    blocks.append(child.block)
                    child.last_used = self._clock
                    matched += bs
                    node = child
                    continue
            # otherwise: best common prefix against ONE more child
            # (full or partial) — adoption stops here, CoW covers
            # the divergent writes
            rem = tuple(tokens[matched: limit])
            best, best_n = None, 0
            for child in node.children.values():
                lim = min(child.n_valid, len(rem))
                n = 0
                while n < lim and child.tokens[n] == rem[n]:
                    n += 1
                if n > best_n:
                    best, best_n = child, n
            if best is not None:
                self.allocator.ref(best.block)
                blocks.append(best.block)
                best.last_used = self._clock
                matched += best_n
            break
        if matched:
            self.n_hits += 1
            self.matched_tokens += matched
        return matched, blocks

    def unrecord_match(self, matched: int) -> None:
        """Roll back the counters of one ``match()`` whose adoption
        was abandoned (admission failed; the engine released the
        adopted references and requeued or shed the request).  A
        queue head retrying every engine step would otherwise record
        one lookup/hit per retry, so ``paging_stats`` could report
        more hits than requests served."""
        self.n_lookups -= 1
        if matched:
            self.n_hits -= 1
            self.matched_tokens -= matched

    def insert(self, tokens, block_ids) -> int:
        """Cache the prefix ``tokens`` whose K/V lives in
        ``block_ids`` (``ceil(len(tokens)/block_size)`` entries — the
        prompt part of a slot's table, immediately after its prefill
        completes).  Existing nodes are kept (their blocks already
        hold identical content — K/V is a deterministic function of
        (prefix, position)); new nodes take one cache-owned reference
        on their block.  Returns the number of newly cached blocks."""
        bs = self.block_size
        self._clock += 1
        node = self._root
        new_blocks = 0
        i = 0
        n = len(tokens)
        while i * bs < n:
            chunk = tuple(tokens[i * bs: min((i + 1) * bs, n)])
            child = node.children.get(chunk)
            if child is None:
                child = _Node(chunk, int(block_ids[i]), node)
                node.children[chunk] = child
                self.allocator.ref(child.block)
                new_blocks += 1
                self.inserted_blocks += 1
            child.last_used = self._clock
            if child.n_valid < bs:
                break           # partial tail: nothing descends past it
            node = child
            i += 1
        return new_blocks

    def evict(self, n_blocks: int) -> int:
        """Free up to ``n_blocks`` blocks by dropping LRU leaves whose
        block the cache alone holds (refcount 1).  Shared leaves
        (a live slot still points at the block) are skipped — their
        turn comes when the slot releases.  Returns blocks actually
        freed.  O(nodes) per eviction — fine at serving scale, where
        eviction is the slow path by construction."""
        freed = 0
        while freed < n_blocks:
            victims = [
                node for node in self._walk(self._root)
                if not node.children
                and self.allocator.refcount(node.block) == 1
            ]
            if not victims:
                break
            victim = min(victims, key=lambda nd: nd.last_used)
            del victim.parent.children[victim.tokens]
            self.allocator.deref(victim.block)   # refcount 1 → freed
            self.evicted_blocks += 1
            freed += 1
        return freed

    def clear(self) -> int:
        """Drop every cached reference (bench arms use this to reset
        warm state between A/B arms).  Returns blocks released."""
        released = 0
        for node in list(self._walk(self._root)):
            self.allocator.deref(node.block)
            released += 1
        self._root = _Node((), None, None)
        return released

    def _walk(self, node: _Node):
        for child in node.children.values():
            yield child
            yield from self._walk(child)
