"""Self-drafting for speculative decoding (serving speed-of-light,
ROADMAP item 1a).

The decode cadence — one token per ``decode_step`` — is itself a
cost: every step pays the full weight-read at batch occupancy, so a
step that COMMITS more than one token divides the per-token weight
traffic.  Speculative decoding gets there without changing the
model: a cheap DRAFTER proposes the next few tokens, one fixed-shape
VERIFY step (``PagedLlamaDecoder.verify``) scores all of them, and
the engine commits the longest proposal prefix the model itself
would have emitted, plus the model's own next token (the "bonus").
Because this repo's samplers are deterministic given (seed,
position) — greedy argmax, or Gumbel-max under a position-folded
key — accept-by-equality reproduces the sequential decode chain
BITWISE at every temperature, not just greedy: the verify row at
position p computes exactly what ``decode`` would compute there.

Drafters are pluggable: anything with ``draft(history, k) ->
list[int]`` (``history`` = prompt + tokens generated so far,
including the committed current token; return UP TO ``k`` proposed
continuations).  The default is host-side self-drafting — no second
model, no device work:

- :class:`NGramDrafter` — prompt-lookahead (the "assisted
  generation" / LLMA trick): find the most recent earlier occurrence
  of the history's trailing n-gram and propose the tokens that
  followed it.  Free accuracy on repetitive continuations (code,
  templated text, shared system prompts, self-repeating greedy
  chains); harmless when wrong — a rejected draft costs only its
  share of the verify window.

A small draft MODEL can slot into the same interface later (wrap its
own decoder in a ``draft`` method); the engine and the verify step
never know the difference.
"""

from __future__ import annotations


class NGramDrafter:
    """Prompt-lookahead n-gram drafter.

    Scans the request's own token history for the most recent prior
    occurrence of the trailing ``n``-gram (longest ``n`` first, down
    to ``min_n``) and proposes the tokens that followed it.  Purely
    host-side and stateless across calls — the history IS the state.

    ``max_scan`` bounds the backward search so drafting stays O(1)
    per step for very long histories (the tail of the history is
    where repetition lives anyway).
    """

    def __init__(self, max_n: int = 3, min_n: int = 1,
                 max_scan: int = 512):
        if not 1 <= min_n <= max_n:
            raise ValueError(
                f"need 1 <= min_n <= max_n, got {min_n}/{max_n}"
            )
        self.max_n = int(max_n)
        self.min_n = int(min_n)
        self.max_scan = int(max_scan)

    def draft(self, history, k: int) -> list:
        """Up to ``k`` proposed continuations of ``history`` (may
        return fewer, or none — the engine degrades to a smaller
        verify window, floor one token/step)."""
        if k <= 0 or not history:
            return []
        h = list(history[-self.max_scan:])
        n_h = len(h)
        for n in range(min(self.max_n, n_h - 1), self.min_n - 1, -1):
            tail = h[n_h - n:]
            # most recent PRIOR occurrence of the trailing n-gram —
            # but a match near the end truncates its continuation at
            # the history boundary (periodic text always matches
            # late), so keep scanning back until a match offers the
            # FULL k-token window
            best: list = []
            for i in range(n_h - n - 1, -1, -1):
                if h[i:i + n] == tail:
                    cont = h[i + n: i + n + k]
                    if len(cont) > len(best):
                        best = cont
                        if len(best) == k:
                            break
            if best:
                return best
        return []
