"""Host-side paged KV-cache block accounting (serving v2).

The v1 cache was slot-contiguous — every admitted request owned
``max_seq`` rows of HBM whether it used 10 tokens or 2048.  Paging
(vLLM's PagedAttention idea) splits the cache into fixed-size BLOCKS
of ``block_size`` token positions and gives each request slot a
BLOCK TABLE: a padded ``int32`` row mapping logical block index →
physical block id.  HBM is then proportional to tokens actually
cached, and two requests can point their tables at the SAME physical
block (a shared prompt prefix) — the sharing/copy-on-write substrate
the radix prefix cache (``serving/prefix_cache.py``) builds on.

Everything here is host-side bookkeeping: the device arrays (the
block pools and the gather/scatter attention over them) live in
``serving/decoder.py``.  Two classes:

- ``BlockAllocator`` — free list + per-block refcounts + loud
  accounting.  Exhaustion raises ``OutOfBlocks`` carrying the full
  allocator state; the engine turns that into an admission-control
  shed (``finish_reason="no_blocks"``) instead of an opaque hang.
- ``BlockManager`` — per-slot block tables over one allocator:
  assignment (adopted shared blocks + fresh ones), incremental
  growth as decode crosses block boundaries, and
  ``ensure_writable`` — the copy-on-write gate every write position
  passes through (a block with refcount > 1 is copied to a fresh
  exclusive block before the first divergent write touches it).

Table rows are padded with the TRASH block id (``n_blocks`` — the
pools allocate one extra physical block for it): writes routed there
are dead by construction and reads of it are masked, so the decode
executable needs no dynamic shapes and no branches.
"""

from __future__ import annotations

import numpy as np


class OutOfBlocks(RuntimeError):
    """KV block pool exhausted.  Carries the allocator state so the
    shed path (and the operator) sees WHY: how many were requested,
    how many are in use / shared / free."""

    def __init__(self, requested: int, state: dict):
        super().__init__(
            f"out of KV-cache blocks: requested {requested}, "
            f"state {state}"
        )
        self.requested = requested
        self.state = state


class BlockAllocator:
    """Free list + refcounts over ``n_blocks`` physical KV blocks.

    A block is born with refcount 1 (its allocator).  Sharing bumps
    the count (``ref``); ``deref`` returns it to the free list at
    zero.  Counters make scarcity loud: ``n_oom`` increments on every
    failed allocation (before ``OutOfBlocks`` raises), ``n_cow``
    counts copy-on-write copies (bumped by ``BlockManager``).
    """

    def __init__(self, n_blocks: int, block_size: int):
        if n_blocks < 1 or block_size < 1:
            raise ValueError(
                f"need n_blocks >= 1 and block_size >= 1, got "
                f"{n_blocks}/{block_size}"
            )
        self.n_blocks = int(n_blocks)
        self.block_size = int(block_size)
        # pop() from the end → lowest ids first (deterministic tables)
        self._free = list(range(self.n_blocks - 1, -1, -1))
        self._ref = np.zeros(self.n_blocks, np.int32)
        self.n_allocs = 0
        self.n_frees = 0
        self.n_cow = 0
        self.n_oom = 0
        self.peak_in_use = 0

    # -- accounting --------------------------------------------------------

    @property
    def blocks_free(self) -> int:
        return len(self._free)

    @property
    def blocks_in_use(self) -> int:
        return self.n_blocks - len(self._free)

    def refcount(self, block: int) -> int:
        return int(self._ref[block])

    def stats(self) -> dict:
        return {
            "n_blocks": self.n_blocks,
            "block_size": self.block_size,
            "blocks_free": self.blocks_free,
            "blocks_in_use": self.blocks_in_use,
            "peak_in_use": self.peak_in_use,
            "n_allocs": self.n_allocs,
            "n_frees": self.n_frees,
            "n_cow": self.n_cow,
            "n_oom": self.n_oom,
        }

    # -- lifecycle ---------------------------------------------------------

    def alloc(self) -> int:
        """One fresh exclusive block (refcount 1), or ``OutOfBlocks``
        — loud, with the full state attached."""
        if not self._free:
            self.n_oom += 1
            raise OutOfBlocks(1, self.stats())
        bid = self._free.pop()
        self._ref[bid] = 1
        self.n_allocs += 1
        self.peak_in_use = max(self.peak_in_use, self.blocks_in_use)
        return bid

    def alloc_many(self, n: int) -> list[int]:
        """``n`` fresh blocks atomically: all or ``OutOfBlocks``
        (nothing leaks on the failure path)."""
        if n > len(self._free):
            self.n_oom += 1
            raise OutOfBlocks(n, self.stats())
        return [self.alloc() for _ in range(n)]

    def ref(self, block: int) -> None:
        """Take one more reference on a live block (prefix adoption /
        cache insertion)."""
        assert self._ref[block] > 0, f"ref of dead block {block}"
        self._ref[block] += 1

    def deref(self, block: int) -> bool:
        """Drop one reference; returns True when this freed the
        block (refcount reached zero → back on the free list)."""
        assert self._ref[block] > 0, f"deref of dead block {block}"
        self._ref[block] -= 1
        if self._ref[block] == 0:
            self._free.append(int(block))
            self.n_frees += 1
            return True
        return False


class BlockManager:
    """Per-slot block tables over one :class:`BlockAllocator`.

    ``tables`` is the host mirror the decoder ships to the device
    every step: ``[max_slots, max_blocks]`` int32, padded with the
    trash block id.  All mutation goes through this class so the
    refcount invariant holds: every non-trash table entry owns
    exactly one reference on its block.
    """

    def __init__(
        self,
        *,
        n_blocks: int | None = None,
        block_size: int,
        max_slots: int,
        max_seq: int,
    ):
        self.block_size = int(block_size)
        self.max_slots = int(max_slots)
        # enough table entries to cover max_seq positions — the ONE
        # derivation of the table width (the decoder's executable
        # shapes adopt it; a second copy of this ceil-div drifting
        # would make gathers read the wrong positions)
        self.max_blocks = -(-int(max_seq) // self.block_size)
        if n_blocks is None:
            # full provisioning (== contiguous HBM); the paged win
            # appears when the caller sets n_blocks BELOW this and
            # admission still succeeds because requests only hold
            # the blocks they use
            n_blocks = self.max_slots * self.max_blocks
        self.allocator = BlockAllocator(n_blocks, block_size)
        self.trash_id = int(n_blocks)   # pools hold one extra block
        self.tables = np.full(
            (self.max_slots, self.max_blocks), self.trash_id, np.int32
        )
        # blocks each slot's table actually owns (prefix of the row)
        self.n_owned = [0] * self.max_slots

    def blocks_for(self, n_tokens: int) -> int:
        """Table entries needed to cover ``n_tokens`` positions."""
        return -(-int(n_tokens) // self.block_size)

    # -- slot lifecycle ----------------------------------------------------

    def assign(self, slot: int, adopted: list[int], n_total: int) -> None:
        """Give ``slot`` a table of ``n_total`` blocks: the
        ``adopted`` shared blocks first (the caller has ALREADY taken
        one reference each — ownership transfers to the table), then
        freshly allocated exclusive ones.  Atomic: on ``OutOfBlocks``
        nothing is assigned and the adopted references are NOT
        consumed (the caller still owns and must release them)."""
        assert self.n_owned[slot] == 0, f"slot {slot} already assigned"
        assert n_total <= self.max_blocks, (n_total, self.max_blocks)
        n_new = n_total - len(adopted)
        fresh = self.allocator.alloc_many(n_new)  # may raise, atomically
        row = list(adopted) + fresh
        self.tables[slot, : len(row)] = row
        self.tables[slot, len(row):] = self.trash_id
        self.n_owned[slot] = len(row)

    def grow(self, slot: int, bidx: int) -> None:
        """Extend ``slot``'s table through block index ``bidx``
        (decode crossed a block boundary).  Raises ``OutOfBlocks``
        atomically when the pool can't cover it."""
        need = bidx + 1 - self.n_owned[slot]
        if need <= 0:
            return
        fresh = self.allocator.alloc_many(need)
        for i, bid in enumerate(fresh):
            self.tables[slot, self.n_owned[slot] + i] = bid
        self.n_owned[slot] += need

    def ensure_writable(self, slot: int, bidx: int, copy_block) -> bool:
        """Copy-on-write gate: if the block at table index ``bidx``
        is SHARED (refcount > 1 — a prefix-cache entry or another
        slot also points at it), copy it to a fresh exclusive block
        via ``copy_block(src, dst)`` (the decoder's jitted
        device-side copy), swap the table entry, and drop the shared
        reference.  Returns True when a copy happened."""
        assert bidx < self.n_owned[slot], (slot, bidx, self.n_owned[slot])
        bid = int(self.tables[slot, bidx])
        if self.allocator.refcount(bid) <= 1:
            return False
        fresh = self.allocator.alloc()            # may raise OutOfBlocks
        copy_block(bid, fresh)
        self.tables[slot, bidx] = fresh
        self.allocator.deref(bid)
        self.allocator.n_cow += 1
        return True

    def free_slot(self, slot: int) -> None:
        """Release every block the slot's table owns (shared blocks
        survive under their remaining references) and reset the row
        to trash."""
        for i in range(self.n_owned[slot]):
            self.allocator.deref(int(self.tables[slot, i]))
        self.tables[slot, :] = self.trash_id
        self.n_owned[slot] = 0

    def slot_blocks(self, slot: int, n: int | None = None) -> list[int]:
        """The first ``n`` (default: all owned) block ids of the
        slot's table."""
        n = self.n_owned[slot] if n is None else n
        assert n <= self.n_owned[slot]
        return [int(b) for b in self.tables[slot, :n]]

    def release_adopted(self, adopted: list[int]) -> None:
        """Failure path of an admission attempt: give back the
        references ``match`` handed out."""
        for bid in adopted:
            self.allocator.deref(bid)
